"""Circuit-breaker backend failover: accelerated path over scalar truth.

The paper's phase-1 notary must vote every period no matter what the
underlying client is doing; our TPU-first stack added a new way to miss
votes the reference never had — a wedged or faulting device path. The
2G2T framing (PAPERS.md) says the fix directly: the verifier must
always be able to fall back to a sound local check when the
accelerated path is suspect. `FailoverSigBackend` is that fallback,
governed by a classic three-state breaker:

- **closed**: calls go to the primary (jax / serving tier). A raising
  primary call is served from the scalar fallback *for that call* and
  counted; `fault_threshold` CONSECUTIVE faults trip the breaker.
- **open**: every call is served from the fallback — the device path
  is not touched at all for `reset_s` seconds.
- **half-open**: after the cooldown, exactly one call becomes a
  differential probe: the fallback computes the authoritative answer,
  the primary recomputes it, and the breaker re-closes ONLY if the two
  agree byte-for-byte. A probe where the PRIMARY raises or disagrees
  re-opens with a fresh cooldown; a probe that reaches NO verdict
  (the fallback raised computing the authoritative answer, or the
  primary shed on backpressure) re-opens without restarting the
  cooldown or counting a primary fault, so the next call re-probes
  immediately. The spot-check matters: a device that "recovers" into
  wrong answers is worse than one that stays down.

The watchdog feeds the breaker through the normal exception path: a
hung dispatch fails its batch's futures with `DeadlineExceeded`, the
failover face catches it like any other primary fault.

Observability: gauge ``resilience/breaker/<name>/state`` (0 closed,
1 half-open, 2 open) plus trip/probe/close/fault/fallback counters in
the metrics registry (surfaced on ``/status`` and the Prometheus
exposition), state-transition log lines, and zero-length
``resilience/breaker/*`` trace events when the span tracer is on.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent import futures
from concurrent.futures import Future
from typing import Callable, Optional

from gethsharding_tpu import metrics, tracing
from gethsharding_tpu.perfwatch import RECORDER
from gethsharding_tpu.resilience.errors import SoundnessViolation
from gethsharding_tpu.sigbackend import SigBackend, VerdictFuture

log = logging.getLogger("resilience.breaker")

CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half-open", OPEN: "open"}


class CircuitBreaker:
    """The state machine, backend-agnostic: callers ask `on_call()` how
    to route ("primary" | "fallback" | "probe") and report outcomes via
    `record_fault` / `record_success` / `probe_matched` /
    `probe_failed`. Env defaults: ``GETHSHARDING_BREAKER_THRESHOLD``
    (consecutive faults to trip, default 3) and
    ``GETHSHARDING_BREAKER_RESET_S`` (open cooldown, default 5)."""

    def __init__(self, name: str = "sigbackend",
                 fault_threshold: Optional[int] = None,
                 reset_s: Optional[float] = None,
                 registry: metrics.Registry = metrics.DEFAULT_REGISTRY,
                 clock: Callable[[], float] = time.monotonic):
        if fault_threshold is None:
            fault_threshold = int(os.environ.get(
                "GETHSHARDING_BREAKER_THRESHOLD", "3"))
        if reset_s is None:
            reset_s = float(os.environ.get(
                "GETHSHARDING_BREAKER_RESET_S", "5.0"))
        if fault_threshold < 1:
            raise ValueError("fault_threshold must be >= 1")
        self.name = name
        self.fault_threshold = fault_threshold
        self.reset_s = reset_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False
        # bumped on every re-close: outcomes of async work submitted
        # BEFORE a recovery (stamped with the epoch at submit time)
        # must not count against the recovered primary
        self._epoch = 0
        base = f"resilience/breaker/{name}"
        self._g_state = registry.gauge(f"{base}/state")
        self._m_trips = registry.counter(f"{base}/trips")
        self._m_closes = registry.counter(f"{base}/closes")
        self._m_probes = registry.counter(f"{base}/probes")
        self._m_probe_mismatches = registry.counter(
            f"{base}/probe_mismatches")
        self._m_faults = registry.counter(f"{base}/primary_faults")

    # -- introspection -----------------------------------------------------

    @property
    def state(self) -> int:
        return self._state

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self._state]

    @property
    def epoch(self) -> int:
        """Staleness stamp for deferred outcomes: capture at submit
        time, hand back to `record_fault`/`record_success` at pull
        time. Bumped on every re-close, so a backlog of watchdog-failed
        futures from BEFORE a recovery cannot re-trip the breaker
        against the recovered primary when the caller finally drains
        them."""
        return self._epoch

    # -- the routing decision ----------------------------------------------

    def on_call(self) -> str:
        """Route one call: 'primary' (closed), 'fallback' (open /
        probe already in flight), or 'probe' (this caller runs the
        differential spot-check)."""
        with self._lock:
            if self._state == CLOSED:
                return "primary"
            if self._state == OPEN and not self._probing \
                    and self._clock() - self._opened_at >= self.reset_s:
                self._state = HALF_OPEN
                self._probing = True
                self._m_probes.inc()
                self._g_state.set(HALF_OPEN)
                self._event("probe")
                return "probe"
            return "fallback"

    # -- outcome reports ---------------------------------------------------

    def record_fault(self, exc: Optional[BaseException] = None,
                     epoch: Optional[int] = None) -> None:
        """One primary fault; trips the breaker at the threshold. An
        `epoch` older than the current one marks a STALE deferred
        outcome (submitted before the last re-close): it is counted on
        the fault metric but not toward tripping."""
        with self._lock:
            self._m_faults.inc()
            if epoch is not None and epoch != self._epoch:
                return
            self._consecutive += 1
            if self._state == CLOSED \
                    and self._consecutive >= self.fault_threshold:
                self._trip_locked(
                    f"{self._consecutive} consecutive primary faults"
                    + (f"; last: {exc!r}" if exc is not None else ""))

    def record_success(self, epoch: Optional[int] = None) -> None:
        with self._lock:
            if epoch is not None and epoch != self._epoch:
                return  # a stale success must not mask fresh faults
            self._consecutive = 0

    def probe_matched(self) -> None:
        """Differential spot-check agreed: re-promote the primary."""
        with self._lock:
            self._state = CLOSED
            self._probing = False
            self._consecutive = 0
            self._epoch += 1
            self._m_closes.inc()
            self._g_state.set(CLOSED)
            self._event("close")
        log.warning("breaker %s closed: half-open probe matched the "
                    "fallback (primary re-promoted)", self.name)

    def probe_failed(self, mismatch: bool,
                     detail: Optional[str] = None) -> None:
        """Probe raised (mismatch=False) or disagreed with the fallback
        (mismatch=True): back to open with a fresh cooldown."""
        with self._lock:
            if mismatch:
                self._m_probe_mismatches.inc()
            else:
                self._m_faults.inc()
            self._state = OPEN
            self._probing = False
            self._opened_at = self._clock()
            self._g_state.set(OPEN)
            self._event("reopen")
        # re-open after a failed probe: ring event only — the trip that
        # opened this episode already dumped its bundle
        RECORDER.record("breaker_reopen", breaker=self.name,
                        mismatch=mismatch, detail=detail)
        log.warning("breaker %s re-opened: probe %s%s", self.name,
                    "MISMATCHED the fallback" if mismatch else "raised",
                    f" ({detail})" if detail else "")

    def probe_aborted(self, detail: Optional[str] = None) -> None:
        """The probe reached no verdict on the primary — the fallback
        raised computing the authoritative answer, or the primary shed
        on backpressure. Back to open, but with the ORIGINAL cooldown
        timestamp and no primary-fault count: the next eligible call
        re-probes immediately instead of benching a possibly-healthy
        primary for a fresh `reset_s` over a non-verdict."""
        with self._lock:
            self._state = OPEN
            self._probing = False
            self._g_state.set(OPEN)
            self._event("probe_abort")
        log.warning("breaker %s probe aborted without a verdict%s",
                    self.name, f" ({detail})" if detail else "")

    def _trip_locked(self, reason: str) -> None:
        self._state = OPEN
        self._probing = False
        self._opened_at = self._clock()
        self._m_trips.inc()
        self._g_state.set(OPEN)
        self._event("trip")
        # a trip is a black-box moment: event into the flight-recorder
        # ring + a post-mortem bundle (the dump IO runs on the
        # recorder's own thread, never under this lock)
        RECORDER.trigger("breaker_trip", dump=True, breaker=self.name,
                         reason=reason)
        log.warning("breaker %s open: %s — serving from the scalar "
                    "fallback for %.1fs before probing", self.name,
                    reason, self.reset_s)

    def _event(self, kind: str) -> None:
        tracer = tracing.TRACER
        if tracer.enabled:
            now = time.monotonic()
            tracer.record(f"resilience/breaker/{kind}", now, now,
                          tags={"breaker": self.name,
                                "state": _STATE_NAMES[self._state]})


class _FailoverFuture:
    """`concurrent.futures.Future`-compatible (on `result`) wrapper
    around a primary async submit: a primary failure surfacing at
    `result()` is recorded as a fault and recomputed on the fallback —
    the waking caller never sees the device error."""

    __slots__ = ("_inner", "_recover", "_on_success", "_done", "_value",
                 "_exc")

    def __init__(self, inner: Future, recover: Callable,
                 on_success: Callable[[], None]):
        self._inner = inner
        self._recover = recover
        self._on_success = on_success
        self._done = False
        self._value = None
        self._exc: Optional[BaseException] = None

    def result(self, timeout=None):
        # idempotent like a real Future: a second result() must not
        # double-count the fault or recompute the fallback — including
        # when the fallback recovery itself raised (the failure is
        # cached and re-raised, not re-derived)
        if self._done:
            if self._exc is not None:
                raise self._exc
            return self._value
        try:
            out = self._inner.result(timeout)
        except (TimeoutError, futures.TimeoutError):
            # the CALLER's timeout on a still-pending batch, not a
            # device fault: re-raise so a later poll can still succeed
            # (both spellings: the classes only merged in python 3.11)
            raise
        except Exception as exc:  # noqa: BLE001 - any primary escape
            try:
                self._value = self._recover(exc)
            except Exception as recover_exc:  # noqa: BLE001
                self._exc = recover_exc
                self._done = True
                raise
            self._done = True
            return self._value
        self._on_success()
        self._value = out
        self._done = True
        return out

    def done(self) -> bool:
        return self._inner.done()

    @property
    def _serving_request(self):
        # tracing passthrough: observe_future_wake attributes caller
        # wake latency via the serving future's request record — hiding
        # it here would silently drop the future_wake span whenever
        # failover wraps the serving tier
        return getattr(self._inner, "_serving_request", None)


class FailoverSigBackend(SigBackend):
    """Drop-in `SigBackend`: primary behind a breaker, scalar fallback.

    Registered as ``failover-python`` / ``failover-jax`` (and composed
    by the node over the serving tier for ``--serving``). `.inner` is
    the primary so backend-nature unwrapping keeps working.
    """

    def __init__(self, primary: SigBackend,
                 fallback: Optional[SigBackend] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 registry: metrics.Registry = metrics.DEFAULT_REGISTRY):
        if fallback is None:
            from gethsharding_tpu.sigbackend import get_backend

            fallback = get_backend("python")
        self.inner = self.primary = primary
        self.fallback = fallback
        self.breaker = breaker or CircuitBreaker(registry=registry)
        self.name = f"failover+{primary.name}"
        base = f"resilience/breaker/{self.breaker.name}"
        self._m_primary_calls = registry.counter(f"{base}/primary_calls")
        self._m_fallback_calls = registry.counter(f"{base}/fallback_calls")

    # -- the routed call core ----------------------------------------------

    @staticmethod
    def _is_backpressure(exc: BaseException) -> bool:
        """Backpressure sheds are the CALLER's weather, not a device
        fault: counting them would trip the breaker (and defeat the
        shed policy with synchronous fallback recomputes) exactly when
        load peaks. Lazy import: the serving tier is optional."""
        from gethsharding_tpu.serving.queue import ServingOverloadError

        return isinstance(exc, ServingOverloadError)

    @staticmethod
    def _is_caller_error(exc: BaseException) -> bool:
        """Deterministic input-validation errors raised at call or
        admission time (ragged rows, wrong types) are the CALLER's
        bug, not a device fault: counting them would let one buggy
        caller trip the breaker and demote a healthy device for
        everyone. They re-raise — the fallback would reject the same
        input. (A ValueError surfacing DURING a half-open probe still
        counts: the fallback accepted that input, so disagreeing on it
        is a primary defect.)"""
        return isinstance(exc, (ValueError, TypeError))

    def _fault(self, exc: BaseException,
               epoch: Optional[int] = None) -> None:
        self.breaker.record_fault(exc, epoch=epoch)
        log.warning("primary sigbackend %s fault (served from %s): %r",
                    self.primary.name, self.fallback.name, exc)

    def _call(self, op: str, *args, decision: Optional[str] = None,
              **kwargs):
        if decision is None:
            decision = self.breaker.on_call()
        if decision == "primary":
            self._m_primary_calls.inc()
            try:
                out = getattr(self.primary, op)(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001 - any device escape
                if self._is_backpressure(exc) or self._is_caller_error(exc):
                    raise  # the caller's problem: fast failure, no fault
                self._fault(exc)
                self._m_fallback_calls.inc()
                return getattr(self.fallback, op)(*args, **kwargs)
            self.breaker.record_success()
            return out
        if decision == "probe":
            # differential spot-check: the fallback's answer is served
            # either way; the primary only re-promotes by AGREEING
            try:
                want = getattr(self.fallback, op)(*args, **kwargs)
            except Exception:
                # the PROBE must conclude even when the fallback itself
                # raises — a dangling _probing flag would bench the
                # primary forever with every later call routed fallback
                self.breaker.probe_aborted("fallback raised during probe")
                raise
            try:
                got = getattr(self.primary, op)(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001
                if self._is_backpressure(exc):
                    # a shed at probe time is the caller's weather, not
                    # a verdict on the device — same exemption as the
                    # closed path: conclude the probe without a fault
                    # or a fresh cooldown so the next call re-probes
                    self.breaker.probe_aborted("primary shed the probe")
                elif isinstance(exc, SoundnessViolation):
                    # the spot-checker inside the primary slot already
                    # compared against the same scalar truth this probe
                    # would have: that IS the differential verdict.
                    # Count it once, on probe_mismatches — not also as
                    # a primary fault (no double-accounting).
                    self.breaker.probe_failed(mismatch=True,
                                              detail=repr(exc))
                else:
                    self.breaker.probe_failed(mismatch=False,
                                              detail=repr(exc))
                return want
            if got == want:
                self.breaker.probe_matched()
            else:
                self.breaker.probe_failed(mismatch=True,
                                          detail=f"op {op}")
            return want
        self._m_fallback_calls.inc()
        return getattr(self.fallback, op)(*args, **kwargs)

    # -- the SigBackend surface --------------------------------------------

    def ecrecover_addresses(self, digests, sigs65):
        return self._call("ecrecover_addresses", digests, sigs65)

    def bls_verify_aggregates(self, messages, agg_sigs, agg_pks):
        return self._call("bls_verify_aggregates", messages, agg_sigs,
                          agg_pks)

    def bls_verify_committees(self, messages, sig_rows, pk_rows,
                              pk_row_keys=None):
        return self._call("bls_verify_committees", messages, sig_rows,
                          pk_rows, pk_row_keys=pk_row_keys)

    def das_verify_samples(self, chunks, indices, proofs, roots):
        return self._call("das_verify_samples", chunks, indices, proofs,
                          roots)

    def das_verify_multiproofs(self, commitments, index_rows, eval_rows,
                               proofs, ns):
        return self._call("das_verify_multiproofs", commitments,
                          index_rows, eval_rows, proofs, ns)

    def bls_verify_committees_async(self, messages, sig_rows, pk_rows,
                                    pk_row_keys=None):
        """The overlapped-audit face: primary-routed submits stay
        async (the fault, if any, surfaces at `result()` and is
        recovered on the fallback there); degraded modes compute
        eagerly and return a resolved future — same contract, no
        overlap, which is exactly the degradation the breaker exists
        to make graceful."""
        decision = self.breaker.on_call()
        if decision == "primary":
            self._m_primary_calls.inc()
            # epoch stamp: this submit's outcome may be pulled long
            # after a watchdog trip and probe recovery — stale faults
            # must not re-trip the breaker against the recovered device
            epoch = self.breaker.epoch
            try:
                inner = self.primary.bls_verify_committees_async(
                    messages, sig_rows, pk_rows, pk_row_keys=pk_row_keys)
            except Exception as exc:  # noqa: BLE001 - submit-time fault
                if self._is_backpressure(exc) or self._is_caller_error(exc):
                    raise
                self._fault(exc, epoch=epoch)
                self._m_fallback_calls.inc()
                out = self.fallback.bls_verify_committees(
                    messages, sig_rows, pk_rows, pk_row_keys=pk_row_keys)
                future = VerdictFuture(lambda: out)
                future.result()
                return future

            # `VerdictFuture.result()` re-runs finalize when it raised
            # (only success is cached), so finalize carries its own
            # failure memo — a caller that polls result() twice on one
            # failed verification must not count two primary faults or
            # re-derive the fallback failure
            state: dict = {}

            def finalize():
                if "exc" in state:
                    raise state["exc"]
                try:
                    out = inner.result()
                except Exception as exc:  # noqa: BLE001 - pull-time fault
                    if (self._is_backpressure(exc)
                            or self._is_caller_error(exc)):
                        # same exemption as the sync path: the caller's
                        # problem surfacing late is still not a device
                        # fault
                        state["exc"] = exc
                        raise
                    self._fault(exc, epoch=epoch)
                    self._m_fallback_calls.inc()
                    try:
                        return self.fallback.bls_verify_committees(
                            messages, sig_rows, pk_rows,
                            pk_row_keys=pk_row_keys)
                    except Exception as fallback_exc:  # noqa: BLE001
                        state["exc"] = fallback_exc
                        raise
                self.breaker.record_success(epoch=epoch)
                return out

            return VerdictFuture(finalize)
        out = self._call("bls_verify_committees", messages, sig_rows,
                         pk_rows, pk_row_keys=pk_row_keys,
                         decision=decision)
        future = VerdictFuture(lambda: out)
        future.result()
        return future

    # -- the serving async face (present iff the primary has one) ----------

    def __getattr__(self, name: str):
        # `submit` exists on this backend only when the primary serves
        # it (a serving-tier primary): callers feature-detect with
        # getattr, and advertising an async face over a scalar primary
        # would be a lie
        if name == "submit" and hasattr(self.primary, "submit"):
            return self._submit
        raise AttributeError(name)

    def _fallback_rows(self, op: str, args, kwargs):
        # admission tags (klass/tenant) are serving-tier vocabulary the
        # scalar fallback's plain SigBackend surface does not speak
        kwargs = {k: v for k, v in kwargs.items()
                  if k not in ("klass", "tenant")}
        return getattr(self.fallback, op)(*args, **kwargs)

    def _submit(self, op: str, *args, **kwargs) -> Future:
        decision = self.breaker.on_call()
        if decision == "primary":
            self._m_primary_calls.inc()
            epoch = self.breaker.epoch  # see bls_verify_committees_async
            try:
                inner = self.primary.submit(op, *args, **kwargs)
            except Exception as exc:  # noqa: BLE001 - admission fault
                if self._is_backpressure(exc) or self._is_caller_error(exc):
                    raise
                self._fault(exc, epoch=epoch)
                self._m_fallback_calls.inc()
                future: Future = Future()
                future.set_result(self._fallback_rows(op, args, kwargs))
                return future

            def recover(exc):
                if self._is_backpressure(exc) or self._is_caller_error(exc):
                    raise exc  # the caller's problem, not a device fault
                self._fault(exc, epoch=epoch)
                self._m_fallback_calls.inc()
                return self._fallback_rows(op, args, kwargs)

            return _FailoverFuture(
                inner, recover,
                lambda: self.breaker.record_success(epoch=epoch))
        future = Future()
        future.set_result(
            self._call(op, *args, decision=decision, **kwargs))
        return future

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        close = getattr(self.primary, "close", None)
        if close is not None:
            close()
