"""Composable retry policies: deadline + capped exponential backoff.

The reference hardens its seams by hand (every caller open-codes its
own poll loop); here retry behavior is ONE object applied at every
seam that talks to something that can transiently fail — the
`SMCClient` RPC-backend reads, shardp2p collation-body fetches, and
`storage/netstore` chunk gets. A seam owns a `RetryExecutor`, which
pre-resolves its per-seam counters once:

- ``resilience/retry/<seam>/retries``  — transient failures absorbed
  (the seam recovered without the caller noticing);
- ``resilience/retry/<seam>/giveups``  — attempts/deadline exhausted,
  the last error re-raised to the caller.

Only *transient* error classes are retried (`RetryPolicy.retryable`);
everything else propagates on the first throw — a revert or a
programming error must never be hammered. Writes are never routed
through an executor (a connection error mid-write is ambiguous;
retrying could double-submit a vote).

Jitter is seedable so chaos tests replay the exact same backoff
timeline run after run.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

from gethsharding_tpu import metrics
from gethsharding_tpu.resilience.errors import FetchAborted, TransientError

# the transient classes every seam agrees on: network-ish failures and
# the layer's own explicit retry signal (chaos InjectedFault subclasses
# ConnectionError on purpose — injected faults model exactly this set)
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, OSError, TransientError)

# OSError subclasses that are deterministic configuration errors, not
# weather: retrying a missing socket path or a permission failure only
# delays the inevitable and masks the misconfiguration
DEFAULT_NON_RETRYABLE: Tuple[Type[BaseException], ...] = (
    FileNotFoundError, PermissionError, IsADirectoryError,
    NotADirectoryError)


class RetryPolicy:
    """Capped exponential backoff with jitter under an overall deadline.

    - ``attempts``: total tries (1 = no retry);
    - ``base_s`` / ``cap_s``: the backoff ladder — try k sleeps
      ``min(cap_s, base_s * 2**k)``, scaled down into
      ``[1 - jitter, 1]`` of itself by the jitter draw;
    - ``deadline_s``: optional wall-clock budget across ALL attempts;
      a retry never starts past it (the sleep is also clipped to the
      remaining budget);
    - ``retryable``: exception classes worth retrying;
    - ``non_retryable``: subclasses carved OUT of `retryable` (the
      deterministic OSError children by default) — re-raised on the
      first throw;
    - ``seed``: fixes the jitter stream (deterministic chaos replays).
    """

    __slots__ = ("attempts", "base_s", "cap_s", "deadline_s", "jitter",
                 "retryable", "non_retryable", "_rng")

    def __init__(self, attempts: int = 4, base_s: float = 0.02,
                 cap_s: float = 1.0, deadline_s: Optional[float] = None,
                 jitter: float = 0.5,
                 retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE,
                 non_retryable: Tuple[Type[BaseException], ...] =
                 DEFAULT_NON_RETRYABLE,
                 seed: Optional[int] = None):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.attempts = attempts
        self.base_s = base_s
        self.cap_s = cap_s
        self.deadline_s = deadline_s
        self.jitter = jitter
        self.retryable = tuple(retryable)
        self.non_retryable = tuple(non_retryable)
        self._rng = random.Random(seed)

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry number `attempt` (0-based)."""
        delay = min(self.cap_s, self.base_s * (2 ** attempt))
        if self.jitter:
            delay *= 1.0 - self.jitter * self._rng.random()
        return delay


class RetryExecutor:
    """One seam's retry loop: policy + pre-resolved per-seam counters.

    ``abort`` is the owner's shutdown hook: called before and after
    every backoff sleep, an exception instance returned from it ends
    the ladder immediately (raised chained to the last transient
    error). Pair it with an interruptible ``sleep`` (e.g. an Event's
    ``wait``) so stop() wakes an in-flight backoff instead of letting
    it run out the full budget against a dead backend.
    """

    def __init__(self, seam: str, policy: Optional[RetryPolicy] = None,
                 registry: metrics.Registry = metrics.DEFAULT_REGISTRY,
                 sleep: Callable[[float], None] = time.sleep,
                 abort: Optional[
                     Callable[[], Optional[BaseException]]] = None):
        self.seam = seam
        self.policy = policy or RetryPolicy()
        self._sleep = sleep
        self._abort = abort
        self._m_retries = registry.counter(
            f"resilience/retry/{seam}/retries")
        self._m_giveups = registry.counter(
            f"resilience/retry/{seam}/giveups")

    def _check_abort(self, cause: BaseException) -> None:
        if self._abort is None:
            return
        stop = self._abort()
        if stop is not None:
            raise stop from cause

    def call(self, fn: Callable, *args, **kwargs):
        """Run `fn` under the policy; re-raise the last transient error
        once attempts (or the deadline) are exhausted."""
        policy = self.policy
        deadline = (time.monotonic() + policy.deadline_s
                    if policy.deadline_s is not None else None)
        for attempt in range(policy.attempts):
            try:
                return fn(*args, **kwargs)
            except policy.retryable as exc:
                if isinstance(exc, policy.non_retryable):
                    raise
                if attempt == policy.attempts - 1:
                    self._m_giveups.inc()
                    raise
                self._check_abort(exc)
                delay = policy.backoff_s(attempt)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self._m_giveups.inc()
                        raise
                    delay = min(delay, remaining)
                self._m_retries.inc()
                if delay > 0:
                    self._sleep(delay)
                self._check_abort(exc)
        raise AssertionError("unreachable")  # pragma: no cover


def retry_call(fn: Callable, *args, seam: str = "adhoc",
               policy: Optional[RetryPolicy] = None, **kwargs):
    """One-shot form for call sites without a long-lived executor."""
    return RetryExecutor(seam, policy).call(fn, *args, **kwargs)


# sentinel: poll_probe exhausted its polls without an answer — the
# caller turns it into its own seam's transient miss (messages and
# retryable tuples stay per-seam)
POLL_MISS = object()


def poll_probe(probe: Callable, wait: Callable[[float], bool], *,
               interval_s: float, polls: int,
               not_ready: Tuple[Type[BaseException], ...]):
    """The shared inner loop of a poll-under-retry attempt.

    Up to `polls` probes, `interval_s` apart, paced by the owning
    service's interruptible `wait` (returning True means the service is
    stopping — raises `FetchAborted`, which is deliberately
    non-transient so the surrounding retry executor aborts instead of
    backing off against a shutting-down service). `probe` raising one
    of `not_ready` means "ask again next poll"; any return value is the
    answer. Returns `POLL_MISS` when every poll came up empty.
    """
    for _ in range(max(1, polls)):
        if wait(interval_s):
            raise FetchAborted
        try:
            return probe()
        except not_ready:
            continue
    return POLL_MISS
