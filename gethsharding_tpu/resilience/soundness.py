"""Continuous statistically-sound integrity audit of the fast path.

The failover breaker (breaker.py) trips on LOUD faults — exceptions,
watchdog timeouts, half-open differential mismatches. A device that
silently returns wrong verdicts at production rate is trusted until
something crashes. Following 2G2T (constant-size, statistically sound
MSM outsourcing, PAPERS.md), `SpotCheckSigBackend` closes that gap the
verifier-side way: fold a cheap re-verification of a seeded-random
subset of rows into a sampled fraction of dispatches, so the
probability that sustained corruption goes undetected decays
geometrically in the number of dispatches — quantified by
`detection_probability`, the same soundness-accounting shape as
`das/sampler.py`.

Two layers, one wrapper:

- **always-on invariant check** (every dispatch, O(rows) python): the
  verdict plane must have exactly one entry per input row, verdict ops
  must answer in the 0/1 domain, ecrecover rows must be None or a
  20-byte address, and rows KNOWN to be rejections without any crypto
  (an empty committee aggregates to the point at infinity and proves
  nothing) must verify False. Catches the cheap-to-catch corruption
  classes — truncated pulls, dtype garbage, stuck-at-True planes —
  for free.
- **sampled spot-check** (probability `rate` per dispatch): re-verify
  `rows` seeded-random rows of the dispatch against the scalar
  reference (`PythonSigBackend`) and compare byte-for-byte. Both the
  per-dispatch decision and the row subset are pure functions of
  (seed, op, dispatch index) — the chaos-schedule idiom — so a run is
  replayable and tests are deterministic.

A detected disagreement raises `SoundnessViolation` (resilience/
errors.py) out of the wrapped call. Composed inside
`FailoverSigBackend`'s primary slot that IS the existing
`record_fault` path: the breaker trips on silent corruption exactly
as it does on loud faults, and a violation surfacing during a
half-open differential probe counts as a probe mismatch (once — the
spot-checker itself never talks to the breaker, so there is no
double-accounting).

Async is first-class: `bls_verify_committees_async` and the serving
`submit` face wrap the inner future and run the audit AT PULL TIME —
the dispatch pipeline never blocks on a scalar recompute, the breaker
epoch stamped by the failover face at submit time governs staleness
(PR 4's rule), and a failure memo guarantees at most one counted
violation per dispatch no matter how often the future is polled.

Observability: per-op ``resilience/soundness/<op>/{checks,rows,
mismatches,invariant_violations}`` counters plus the ``rate`` gauge in
the metrics registry (surfaced on ``/status``, the Prometheus
exposition), and ``resilience/soundness/violation`` trace events when
the span tracer is on.
"""

from __future__ import annotations

import math
import os
import random
import threading
import time
from concurrent import futures
from typing import Dict, List, Optional, Sequence, Tuple

from gethsharding_tpu import metrics, slo, tracing
from gethsharding_tpu.perfwatch import RECORDER
from gethsharding_tpu.resilience.errors import SoundnessViolation
from gethsharding_tpu.sigbackend import SigBackend, VerdictFuture

# the default sampled fraction of dispatches: at 4 checked rows per
# 64-row dispatch this re-verifies ~0.3% of all rows — inside the <2%
# overhead budget bench.py --soundness asserts — while catching an
# every-dispatch single-row corruptor within ~1500 dispatches at 99%
# confidence (seconds at production dispatch rates; corrupting MORE
# rows per dispatch, or a larger share of dispatches, detects faster)
DEFAULT_RATE = 0.05
DEFAULT_ROWS = 4

# the ops carrying consensus verdicts; everything the audit covers
AUDITED_OPS = ("ecrecover_addresses", "bls_verify_aggregates",
               "bls_verify_committees", "das_verify_samples",
               "das_verify_multiproofs")
_VERDICT_OPS = ("bls_verify_aggregates", "bls_verify_committees",
                "das_verify_samples", "das_verify_multiproofs")


# == the soundness accounting behind (rate, rows) ==========================


def detection_probability(rate: float, rows_checked: int, batch_rows: int,
                          corrupt_rows: int = 1,
                          dispatches: int = 1) -> float:
    """P(the spot-checker catches corruption within `dispatches`
    dispatches), against an adversary/fault corrupting `corrupt_rows`
    of every `batch_rows`-row dispatch.

    Per dispatch: the check fires with probability `rate` and samples
    `rows_checked` distinct rows; it misses every corrupted row with
    probability C(batch_rows - corrupt_rows, s) / C(batch_rows, s)
    = prod_{i<s} (clean - i)/(batch_rows - i). Dispatch decisions are
    independent, so `dispatches` dispatches all escape with the
    per-dispatch miss probability to that power — the complement is
    returned. Mirrors `das/sampler.detection_probability`."""
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"rate must be in [0, 1], got {rate}")
    if batch_rows <= 0 or corrupt_rows <= 0 or corrupt_rows > batch_rows:
        raise ValueError(
            f"bad shape batch_rows={batch_rows} corrupt_rows={corrupt_rows}")
    s = min(rows_checked, batch_rows)
    clean = batch_rows - corrupt_rows
    miss = 1.0
    for i in range(s):
        if clean - i <= 0:
            miss = 0.0
            break
        miss *= (clean - i) / (batch_rows - i)
    p_dispatch = rate * (1.0 - miss)
    return 1.0 - (1.0 - p_dispatch) ** max(1, dispatches)


def dispatches_to_detect(rate: float, rows_checked: int, batch_rows: int,
                         corrupt_rows: int = 1,
                         confidence: float = 0.99) -> int:
    """The dispatch budget: how many corrupted dispatches until the
    spot-checker has caught one with probability >= `confidence`. The
    number the closed-loop acceptance runs assert against."""
    if not 0.0 < confidence < 1.0:
        raise ValueError(f"confidence must be in (0, 1), got {confidence}")
    p = detection_probability(rate, rows_checked, batch_rows, corrupt_rows)
    if p <= 0.0:
        raise ValueError(
            f"detection probability is 0 at rate={rate} "
            f"rows_checked={rows_checked} — corruption is undetectable")
    if p >= 1.0:
        return 1
    return max(1, math.ceil(math.log(1.0 - confidence)
                            / math.log(1.0 - p)))


def soundness_table(batch_rows: int = 64, rows_checked: int = DEFAULT_ROWS,
                    rates: Sequence[float] = (0.01, 0.05, 0.25, 1.0),
                    corrupt_rows: int = 1,
                    confidence: float = 0.99) -> List[dict]:
    """Rows for the README soundness table: sample rate vs per-dispatch
    detection probability and the dispatch budget to `confidence` —
    the `das/sampler.soundness_table` shape for the audit plane."""
    return [{"rate": rate,
             "p_detect_per_dispatch": detection_probability(
                 rate, rows_checked, batch_rows, corrupt_rows),
             f"dispatches_p{int(confidence * 100)}": dispatches_to_detect(
                 rate, rows_checked, batch_rows, corrupt_rows, confidence)}
            for rate in rates]


# == the audited futures ===================================================


class _SpotCheckFuture:
    """`concurrent.futures.Future`-compatible (on `result`) wrapper
    that runs the soundness audit AT PULL TIME: the dispatch pipeline
    (serving flush thread, staged device launch) never blocks on the
    scalar recompute; the caller that pulls the verdict pays it.

    The failure memo makes the audit count at most once per dispatch:
    a caller polling a violated future twice re-raises the CACHED
    `SoundnessViolation` instead of re-running the check (which would
    double-count the mismatch counters — and, composed under the
    failover face, the failover future's own memo already guarantees a
    single `record_fault`). A caller-timeout on a still-pending batch
    re-raises un-memoized so a later poll can still succeed."""

    __slots__ = ("_inner", "_audit", "_done", "_value", "_exc")

    def __init__(self, inner, audit):
        self._inner = inner
        self._audit = audit
        self._done = False
        self._value = None
        self._exc: Optional[BaseException] = None

    def result(self, timeout=None):
        if self._done:
            if self._exc is not None:
                raise self._exc
            return self._value
        try:
            out = self._inner.result(timeout)
        except (TimeoutError, futures.TimeoutError):
            # the CALLER's timeout, not an outcome: leave un-memoized
            # (both spellings: the classes only merged in python 3.11)
            raise
        except Exception as exc:  # noqa: BLE001 - any inner escape
            # a loud device fault is the breaker's existing territory;
            # memoize so a re-poll re-raises without re-pulling
            self._exc = exc
            self._done = True
            self._audit = None  # drop the captured input columns
            raise
        try:
            self._audit(out)
        except Exception as exc:  # noqa: BLE001 - the violation
            self._exc = exc
            self._done = True
            self._audit = None
            raise
        self._value = out
        self._done = True
        self._audit = None
        return out

    def done(self) -> bool:
        done = getattr(self._inner, "done", None)
        return self._done or (bool(done()) if done is not None else False)

    @property
    def _serving_request(self):
        # tracing passthrough (same contract as _FailoverFuture):
        # observe_future_wake attributes caller wake latency via the
        # serving future's request record — hiding it here would drop
        # the future_wake span whenever the spot-checker wraps serving
        return getattr(self._inner, "_serving_request", None)


# == the wrapper ===========================================================


class SpotCheckSigBackend(SigBackend):
    """Drop-in `SigBackend` folding a continuous soundness audit into
    every dispatch of the wrapped backend.

    Composable under `ServingSigBackend` (checks run in the dispatch
    thread, per coalesced batch) or OVER it (checks run per caller
    request at pull time), and inside `FailoverSigBackend`'s primary
    slot — the intended production shape, where a raised
    `SoundnessViolation` is a primary fault that trips the breaker.

    - ``rate``: probability a dispatch is spot-checked
      (``GETHSHARDING_SOUNDNESS_RATE``, default 0.05);
    - ``rows``: rows re-verified per checked dispatch
      (``GETHSHARDING_SOUNDNESS_ROWS``, default 4);
    - ``seed``: selection seed (``GETHSHARDING_SOUNDNESS_SEED``) — the
      per-dispatch decision and the row subset are pure functions of
      (seed, op, dispatch index), replayable like a chaos schedule;
    - ``reference``: the scalar truth (default `PythonSigBackend`).
    """

    def __init__(self, inner: SigBackend,
                 rate: Optional[float] = None,
                 rows: Optional[int] = None,
                 reference: Optional[SigBackend] = None,
                 seed: Optional[int] = None,
                 registry: metrics.Registry = metrics.DEFAULT_REGISTRY):
        # empty-string env values read as unset, like every other
        # reader of these variables (node/backend.py, node/cli.py)
        if rate is None:
            rate = float(os.environ.get("GETHSHARDING_SOUNDNESS_RATE", "")
                         or DEFAULT_RATE)
        if rows is None:
            rows = int(os.environ.get("GETHSHARDING_SOUNDNESS_ROWS", "")
                       or DEFAULT_ROWS)
        if seed is None:
            seed = int(os.environ.get("GETHSHARDING_SOUNDNESS_SEED", "")
                       or 0)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"soundness rate must be in [0, 1], got {rate}")
        if rows < 1:
            raise ValueError(f"soundness rows must be >= 1, got {rows}")
        if reference is None:
            from gethsharding_tpu.sigbackend import PythonSigBackend

            reference = PythonSigBackend()
        self.inner = inner
        self.rate = rate
        self.rows = rows
        self.seed = seed
        self.reference = reference
        self.name = f"soundness+{inner.name}"
        self._lock = threading.Lock()
        self._dispatches: Dict[str, int] = {}
        base = "resilience/soundness"
        registry.gauge(f"{base}/rate").set(rate)
        self._m = {op: {"checks": registry.counter(f"{base}/{op}/checks"),
                        "rows": registry.counter(f"{base}/{op}/rows"),
                        "mismatches": registry.counter(
                            f"{base}/{op}/mismatches"),
                        "invariant_violations": registry.counter(
                            f"{base}/{op}/invariant_violations")}
                   for op in AUDITED_OPS}

    # -- introspection -----------------------------------------------------

    def describe(self) -> dict:
        """The operator summary `/status` embeds: the configured knobs
        plus what they buy — per-dispatch detection probability and the
        99%-confidence dispatch budget at a representative 64-row
        dispatch with one corrupted row (the hardest-to-hit case: more
        corrupted rows only detect faster)."""
        return {
            "rate": self.rate,
            "rows_per_check": self.rows,
            "reference": self.reference.name,
            "p_detect_per_dispatch_64": round(
                detection_probability(self.rate, self.rows, 64), 6),
            "dispatches_p99_64": dispatches_to_detect(
                self.rate, self.rows, 64) if self.rate > 0 else None,
        }

    # -- the decision plane (the chaos-schedule idiom) ---------------------

    def _tick(self, op: str) -> Tuple[bool, int]:
        """Consume one dispatch slot on `op`; returns (check?, index).
        The verdict for dispatch k never depends on other ops' traffic."""
        with self._lock:
            idx = self._dispatches.get(op, 0)
            self._dispatches[op] = idx + 1
        if self.rate <= 0.0:
            return False, idx
        if self.rate >= 1.0:
            return True, idx
        verdict = random.Random(
            f"{self.seed}:{op}:{idx}").random() < self.rate
        return verdict, idx

    def _select_rows(self, op: str, idx: int, n: int) -> List[int]:
        k = min(self.rows, n)
        return sorted(random.Random(
            f"{self.seed}:{op}:{idx}:rows").sample(range(n), k))

    # -- violation plumbing ------------------------------------------------

    def _violation(self, op: str, kind: str, detail: str) -> None:
        self._m[op][("mismatches" if kind == "mismatch"
                     else "invariant_violations")].inc()
        # the integrity SLO: every violation burns the integrity
        # objective's error budget, so the 2G2T detection budget reads
        # as a burn rate, not just a counter (slo/tracker.py)
        slo.record(slo.INTEGRITY, ok=False)
        tracer = tracing.TRACER
        if tracer.enabled:
            now = time.monotonic()
            tracer.record("resilience/soundness/violation", now, now,
                          tags={"op": op, "kind": kind})
        # silent corruption detected: black-box moment — bundle dumped
        # (async) with the event/span/wire rings leading up to it
        RECORDER.trigger("soundness_violation", dump=True, op=op,
                         violation_kind=kind, detail=detail)
        raise SoundnessViolation(
            f"soundness {kind} on {op}: {detail} "
            f"(backend {self.inner.name} vs reference "
            f"{self.reference.name})")

    # -- the always-on verdict-plane invariant check -----------------------

    def _check_invariants(self, op: str, cols: Tuple, out) -> None:
        """O(rows) pure-python sanity of the verdict plane — runs on
        EVERY dispatch, sampled or not. Catches the corruption classes
        that need no crypto to catch."""
        n = len(cols[0]) if cols else 0
        try:
            got_n = len(out)
        except TypeError:
            self._violation(op, "invariant",
                            f"result is not a sequence: {type(out).__name__}")
        if got_n != n:
            self._violation(op, "invariant",
                            f"{got_n} result rows for {n} input rows")
        if op == "ecrecover_addresses":
            for i, addr in enumerate(out):
                if addr is None:
                    continue
                try:
                    size = len(addr)
                except TypeError:
                    size = -1
                if size != 20:
                    self._violation(op, "invariant",
                                    f"row {i}: recovered address is not "
                                    f"None or 20 bytes ({addr!r})")
            return
        for i, verdict in enumerate(out):
            # the 0/1 domain: a verdict plane pulled off the device must
            # decode to exactly True or False — ints outside {0, 1},
            # floats, strings are dtype/transfer corruption
            if not (isinstance(verdict, bool)
                    or (isinstance(verdict, int) and verdict in (0, 1))
                    or (hasattr(verdict, "dtype") and verdict in (0, 1))):
                self._violation(op, "invariant",
                                f"row {i}: verdict {verdict!r} outside "
                                f"the 0/1 domain")
        if op == "bls_verify_committees":
            # the known-infinity rows: an empty committee aggregates to
            # the point at infinity and proves nothing — True here is
            # corruption no matter what the device claims
            _, sig_rows, pk_rows = cols
            for i, (sigs, pks) in enumerate(zip(sig_rows, pk_rows)):
                if (len(sigs) == 0 or len(pks) == 0) and bool(out[i]):
                    self._violation(op, "invariant",
                                    f"row {i}: empty committee row "
                                    f"verified True")

    # -- the sampled spot-check --------------------------------------------

    def _spot_check(self, op: str, cols: Tuple, out, idx: int) -> None:
        n = len(cols[0]) if cols else 0
        if n == 0:
            return
        picked = self._select_rows(op, idx, n)
        sub = [[col[i] for i in picked] for col in cols]
        want = getattr(self.reference, op)(*sub)
        got = [out[i] for i in picked]
        counters = self._m[op]
        counters["checks"].inc()
        counters["rows"].inc(len(picked))
        # normalize to plain bools for the verdict ops so a numpy bool
        # from the device compares by VALUE against the scalar python
        if op in _VERDICT_OPS:
            got = [bool(v) for v in got]
            want = [bool(v) for v in want]
        if got != want:
            bad = [picked[j] for j in range(len(picked))
                   if got[j] != want[j]]
            self._violation(op, "mismatch",
                            f"dispatch {idx}, rows {bad}: device said "
                            f"{[got[picked.index(i)] for i in bad]}, "
                            f"reference says "
                            f"{[want[picked.index(i)] for i in bad]}")
        # a clean spot-check is one GOOD integrity event: the SLO's
        # event stream runs at the sampled check rate, so its burn rate
        # is the detected-corruption fraction of audited dispatches
        slo.record(slo.INTEGRITY, ok=True)

    def _audit(self, op: str, cols: Tuple, out) -> None:
        self._check_invariants(op, cols, out)
        check, idx = self._tick(op)
        if check:
            self._spot_check(op, cols, out, idx)

    # -- the SigBackend surface --------------------------------------------

    def ecrecover_addresses(self, digests, sigs65):
        cols = (list(digests), list(sigs65))
        out = self.inner.ecrecover_addresses(*cols)
        self._audit("ecrecover_addresses", cols, out)
        return out

    def bls_verify_aggregates(self, messages, agg_sigs, agg_pks):
        cols = (list(messages), list(agg_sigs), list(agg_pks))
        out = self.inner.bls_verify_aggregates(*cols)
        self._audit("bls_verify_aggregates", cols, out)
        return out

    def bls_verify_committees(self, messages, sig_rows, pk_rows,
                              pk_row_keys=None):
        cols = (list(messages), list(sig_rows), list(pk_rows))
        out = self.inner.bls_verify_committees(*cols,
                                               pk_row_keys=pk_row_keys)
        # the reference recompute never sees pk_row_keys: the scalar
        # backend has no cache, and the check must not depend on one
        self._audit("bls_verify_committees", cols, out)
        return out

    def das_verify_samples(self, chunks, indices, proofs, roots):
        cols = (list(chunks), list(indices), list(proofs), list(roots))
        out = self.inner.das_verify_samples(*cols)
        self._audit("das_verify_samples", cols, out)
        return out

    def das_verify_multiproofs(self, commitments, index_rows, eval_rows,
                               proofs, ns):
        # the spot check re-verifies sampled rows against the scalar
        # PCS reference (PythonSigBackend -> das/pcs.verify_multi) —
        # the batched pairing path has no verdict blind spot
        cols = (list(commitments), list(index_rows), list(eval_rows),
                list(proofs), list(ns))
        out = self.inner.das_verify_multiproofs(*cols)
        self._audit("das_verify_multiproofs", cols, out)
        return out

    def bls_verify_committees_async(self, messages, sig_rows, pk_rows,
                                    pk_row_keys=None):
        """The overlapped-audit face: the inner submit stays async and
        the audit runs at `result()` time — marshal/dispatch overlap is
        preserved, the scalar recompute lands on the puller. Composed
        under the failover face, the violation surfaces inside ITS
        finalize, which already stamps the submit-time breaker epoch
        and memoizes the fault (at most one per dispatch)."""
        cols = (list(messages), list(sig_rows), list(pk_rows))
        inner = self.inner.bls_verify_committees_async(
            *cols, pk_row_keys=pk_row_keys)
        state: dict = {}

        def finalize():
            # `VerdictFuture.result()` re-runs finalize when it raised:
            # carry a failure memo so a twice-polled violated dispatch
            # counts exactly one mismatch
            if "exc" in state:
                raise state["exc"]
            out = inner.result()
            try:
                self._audit("bls_verify_committees", cols, out)
            except SoundnessViolation as exc:
                state["exc"] = exc
                raise
            return out

        return VerdictFuture(finalize)

    # -- the serving async face (present iff the inner has one) ------------

    def __getattr__(self, name: str):
        # same feature-detection contract as the failover face: `submit`
        # exists on this backend only when the wrapped backend serves it
        if name == "submit" and hasattr(self.inner, "submit"):
            return self._submit
        raise AttributeError(name)

    def _submit(self, op: str, *args, pk_row_keys=None, **kwargs):
        # admission tags (klass/tenant) pass through untouched — the
        # audit has no opinion on queueing policy
        cols = tuple(list(col) for col in args)
        if op == "bls_verify_committees":
            inner = self.inner.submit(op, *cols, pk_row_keys=pk_row_keys,
                                      **kwargs)
        else:
            inner = self.inner.submit(op, *cols, **kwargs)
        if op not in AUDITED_OPS:  # pragma: no cover - SERVING_OPS today
            return inner
        return _SpotCheckFuture(inner,
                                audit=lambda out: self._audit(op, cols, out))

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()
