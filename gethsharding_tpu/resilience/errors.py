"""The resilience layer's error vocabulary.

Kept in a leaf module so infrastructure that FAILS work (the serving
dispatcher, the watchdog) and infrastructure that RETRIES it (the
policy executors) can share one vocabulary without importing each
other. `serving/pipeline.py` imports from here; nothing here imports
anything.
"""

from __future__ import annotations


class ResilienceError(RuntimeError):
    """Base class for the resilience layer's own failure signals."""


class DeadlineExceeded(ResilienceError):
    """An in-flight operation overran its deadline and was abandoned.

    Raised into the futures of a batch whose device dispatch the
    watchdog declared hung. Callers already handle errored batches
    (the serving tier fails a batch's futures on any dispatch error);
    the distinct type lets a failover backend count it as a device
    fault rather than a caller mistake.
    """


class DispatcherClosed(ResilienceError):
    """Work was still queued (or in flight) when the dispatcher shut
    down; its futures are failed with this instead of hanging."""


class SoundnessViolation(ResilienceError):
    """The primary backend returned a result the soundness audit
    rejects: a randomized spot-check row disagreed with the scalar
    reference, or the always-on verdict-plane invariant check failed
    (wrong row count, out-of-domain verdict, an empty committee row
    verifying True).

    This is SILENT corruption made loud: the device path raised
    nothing, the answer was simply wrong. A `ResilienceError` (not a
    ValueError/TypeError) on purpose — the failover face must count it
    as a primary fault so the breaker trips on a corrupting device
    exactly as it does on a crashing one, and during a half-open
    differential probe it counts as a probe MISMATCH (the spot-check
    compared against the same scalar truth the probe would have).
    """


class TransientError(Exception):
    """A failure the caller expects to succeed on retry.

    Seam adapters (netstore fetch misses, collation-body waits) raise
    subclasses of this so the default `RetryPolicy.retryable` tuple
    picks them up without widening to bare Exception.
    """


class FetchAborted(Exception):
    """A poll-under-retry seam is stopping mid-fetch.

    Deliberately NOT transient (plain Exception, not TransientError):
    the retry executor must abort immediately instead of backing off
    and re-polling against a shutting-down service. Raised by
    `policy.poll_probe` when the owning service's `wait` reports stop.
    """
