"""Deterministic chaos injection: seeded failure schedules at the seams.

The reference hardens with hand-written doubles (faultyReader /
faultyCaller subclasses per test); this module replaces that with ONE
reusable injection surface driven by a seeded, replayable schedule:

- **mainchain-call seam** — ``wrap(backend, schedule, "mainchain")``
  puts a fault-injecting proxy in front of a chain backend, UNDER the
  `SMCClient` retry executor (so retry-then-succeed paths are real);
  ``wrap(client, schedule, "client")`` fronts the client itself for
  faults the backend never sees (keystore signs);
- **backend-op seam** — `ChaosSigBackend` fronts any `SigBackend`;
  scheduled ``backend.<op>`` entries raise `InjectedFault` (a device
  fault the failover breaker counts), scheduled ``dispatch.<op>``
  entries HANG for `hang_s` seconds (a wedged dispatch the watchdog
  must catch); a ``backend.<op>`` seam tagged ``mode=corrupt``
  (``"backend.bls_verify_committees:mode=corrupt"`` in a spec, or the
  whole plane via ``"backend.*:mode=corrupt"``) raises NOTHING —
  scheduled calls return a seeded, silently CORRUPTED result (verdict
  bits flipped, a recovered address perturbed), the failure class only
  the soundness spot-checker (`resilience/soundness.py`) can catch;
- the schedule itself is pure decision logic: per-seam call counters
  plus a seed, so the SAME spec replays the SAME failure timeline in
  tests, `bench.py --chaos`, and a devnet node booted with
  ``--chaos`` — no `random` module state leaks between runs.

`InjectedFault` subclasses `ConnectionError` deliberately: injected
faults model transient infrastructure failure, the class the retry
policies treat as retryable.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from gethsharding_tpu import metrics
from gethsharding_tpu.perfwatch import RECORDER
from gethsharding_tpu.sigbackend import SigBackend


class InjectedFault(ConnectionError):
    """A deterministically scheduled failure (retryable by design)."""


# a seam rule's failure mode: "fault" raises InjectedFault (the loud
# default), "corrupt" silently perturbs the result (backend.* seams
# only — the silent-corruption chaos the soundness audit must catch),
# "delay" stalls the wire call for `delay_s` before letting it through
# and "partition" makes the wire unreachable (both on the
# ``fleet.transport`` seam only — the tail-latency and network-split
# failure classes request hedging and the router's trip path exist for)
MODES = ("fault", "corrupt", "delay", "partition")

# the one seam with a wire to delay or partition: the router-side
# transport in front of a replica (fleet/router.py TransportChaos /
# RpcReplicaBackend)
TRANSPORT_SEAM = "fleet.transport"


class ChaosSchedule:
    """Seeded per-seam failure schedule.

    ``rules`` maps a seam name (e.g. ``"mainchain.collation_record"``,
    ``"backend.bls_verify_committees"``, ``"dispatch.ecrecover_addresses"``)
    — or a bare seam prefix (``"mainchain"``) matching every op under
    it — to one of:

    - ``True``            fail every call;
    - ``int n``           fail the first n calls (then heal — the
                          retry-then-succeed / breaker-recovery shape);
    - ``float r in (0,1)``  fail each call with probability r, decided
                          by a hash of (seed, seam, call index) so the
                          verdict for call k never depends on how many
                          other seams fired;
    - ``callable(idx)``   arbitrary predicate on the per-seam call index.

    ``modes`` maps a seam (same exact-or-bare-prefix resolution) to a
    failure mode from `MODES`; unmapped seams default to ``"fault"``.
    The schedule stays pure decision logic — `mode_for` only REPORTS
    the mode, the injector at the seam acts on it. ``delay_s`` is the
    stall a ``mode=delay`` transport injector applies per scheduled
    call (the schedule carries it so one spec replays one timeline).
    """

    def __init__(self, seed: int = 0, rules: Optional[Dict] = None,
                 modes: Optional[Dict[str, str]] = None,
                 delay_s: float = 0.25):
        self.seed = seed
        self.rules = dict(rules or {})
        self.modes = dict(modes or {})
        self.delay_s = delay_s
        for seam, mode in self.modes.items():
            if mode not in MODES:
                raise ValueError(
                    f"unknown chaos mode {mode!r} for seam {seam!r}; "
                    f"choose from {MODES}")
            if mode == "corrupt" and seam != "backend" \
                    and not seam.startswith("backend."):
                # only the backend-op seam has a result to corrupt;
                # accepting corrupt on mainchain.*/dispatch.* would
                # silently degrade to every-call LOUD faults — the
                # opposite of what the operator asked to test
                raise ValueError(
                    f"mode=corrupt is only supported on backend.* seams, "
                    f"not {seam!r} (mainchain/dispatch seams have no "
                    f"result plane to corrupt)")
            if mode in ("delay", "partition") and seam != TRANSPORT_SEAM:
                # only the wire has latency to stretch or a link to cut;
                # a delayed backend op would be dispatch.* hang territory
                raise ValueError(
                    f"mode={mode} is only supported on the "
                    f"{TRANSPORT_SEAM!r} seam, not {seam!r} (only the "
                    f"replica wire has a transport to {mode})")
        self.injected: Dict[str, int] = {}
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._m_injected = metrics.counter("resilience/chaos/injected")

    def _rule_for(self, seam: str):
        rule = self.rules.get(seam)
        if rule is None and "." in seam:
            rule = self.rules.get(seam.split(".", 1)[0])
        return rule

    def has_rule(self, seam: str) -> bool:
        """True when a rule (exact or bare-prefix) names this seam."""
        rule = self._rule_for(seam)
        return rule is not None and rule is not False

    def mode_for(self, seam: str) -> str:
        """The seam's failure mode (exact match wins over bare prefix;
        default "fault")."""
        mode = self.modes.get(seam)
        if mode is None and "." in seam:
            mode = self.modes.get(seam.split(".", 1)[0])
        return mode or "fault"

    def should_fail(self, seam: str) -> bool:
        """Consume one call slot on `seam`; True = inject."""
        return self.decide(seam)[0]

    def decide(self, seam: str) -> Tuple[bool, int]:
        """Consume one call slot on `seam`; returns (inject?, index).
        The index makes corruption REPLAYABLE: a corrupt-mode injector
        seeds its perturbation from (seed, seam, index), so the same
        spec flips the same bits in the same calls every run."""
        with self._lock:
            idx = self._counts.get(seam, 0)
            self._counts[seam] = idx + 1
        rule = self._rule_for(seam)
        if rule is None or rule is False:
            return False, idx
        if rule is True:
            verdict = True
        elif isinstance(rule, bool):  # pragma: no cover - covered above
            verdict = rule
        elif isinstance(rule, int):
            verdict = idx < rule
        elif isinstance(rule, float):
            verdict = random.Random(
                f"{self.seed}:{seam}:{idx}").random() < rule
        else:
            verdict = bool(rule(idx))
        if verdict:
            with self._lock:
                self.injected[seam] = self.injected.get(seam, 0) + 1
            self._m_injected.inc()
            # every injection decision lands in the flight-recorder
            # ring: a post-mortem bundle must say whether the chaos
            # harness, not the device, caused the episode
            RECORDER.record("chaos_decision", seam=seam, index=idx,
                            mode=self.mode_for(seam))
        return verdict, idx

    def fire(self, seam: str) -> None:
        """Raise `InjectedFault` when the schedule says this call fails."""
        if self.should_fail(seam):
            raise InjectedFault(
                f"chaos: injected fault at {seam} "
                f"(call {self._counts[seam] - 1}, seed {self.seed})")

    def calls(self, seam: str) -> int:
        with self._lock:
            return self._counts.get(seam, 0)


def parse_spec(spec: str) -> ChaosSchedule:
    """Parse the CLI/bench chaos spec string.

    ``"seed=7,backend.bls_verify_committees=2,mainchain.collation_record=0.3,client.sign=always"``
    — `seed=` names the schedule seed; every other entry is a seam
    rule: ``always`` -> True, a value containing ``.`` -> float rate,
    otherwise -> int first-n.

    A ``<seam>:mode=corrupt`` entry tags the seam's failure mode
    (``backend.ecrecover_addresses:mode=corrupt``); a mode entry with
    no rule of its own defaults the seam's rule to every-call. A seam
    written ``backend.*`` is the bare prefix ``backend`` (every op
    under it). ``delay_s=`` names the transport-delay stall for
    ``fleet.transport:mode=delay`` entries
    (``"fleet.transport=0.3,fleet.transport:mode=delay,delay_s=0.1"``).
    Malformed mode entries fail fast with the offending
    token — a typo'd mode silently injecting nothing (or loudly
    instead of silently) would test less than the operator asked for.
    """
    seed = 0
    delay_s = 0.25
    rules: Dict = {}
    modes: Dict[str, str] = {}
    mode_only: List[str] = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if "=" not in part:
            raise ValueError(f"chaos spec entry {part!r} is not key=value")
        key, value = (s.strip() for s in part.split("=", 1))
        if key.endswith(".*"):  # backend.* == the bare prefix rule
            key = key[:-2]
        if key == "seed":
            seed = int(value)
        elif key == "delay_s":
            delay_s = float(value)
        elif ":" in key:
            seam, attr = (s.strip() for s in key.split(":", 1))
            if seam.endswith(".*"):
                seam = seam[:-2]
            if attr != "mode":
                raise ValueError(
                    f"chaos spec entry {part!r}: unknown seam attribute "
                    f"{attr!r} (only 'mode' is supported)")
            if value not in MODES:
                raise ValueError(
                    f"chaos spec entry {part!r}: unknown mode {value!r}; "
                    f"choose from {MODES}")
            modes[seam] = value
            mode_only.append(seam)
        elif value == "always":
            rules[key] = True
        elif "." in value:
            rules[key] = float(value)
        else:
            rules[key] = int(value)
    for seam in mode_only:
        # a mode entry alone means "every call, in that mode"
        rules.setdefault(seam, True)
    return ChaosSchedule(seed=seed, rules=rules, modes=modes,
                         delay_s=delay_s)


def transport_disturb(schedule: Optional[ChaosSchedule]) -> None:
    """Consume one ``fleet.transport`` slot and act on it: ``delay``
    stalls the calling (wire) thread `schedule.delay_s` seconds before
    letting the call proceed — the slow-link tail the router's hedging
    exists to cut; ``partition`` (and plain ``fault``) raise
    `InjectedFault`, the unreachable-replica failure the router's
    consecutive-transport-failure trip absorbs. One seam, both the
    in-process `TransportChaos` front and `RpcReplicaBackend`'s real
    wire consult it, so a bench fleet and a cross-process fleet replay
    the same timeline from the same spec."""
    if schedule is None or not schedule.has_rule(TRANSPORT_SEAM):
        return
    inject, idx = schedule.decide(TRANSPORT_SEAM)
    if not inject:
        return
    mode = schedule.mode_for(TRANSPORT_SEAM)
    if mode == "delay":
        time.sleep(schedule.delay_s)
        return
    raise InjectedFault(
        f"chaos: transport {mode} at {TRANSPORT_SEAM} "
        f"(call {idx}, seed {schedule.seed})")


class TransportChaos:
    """A transport-seam front for an IN-PROCESS replica backend: every
    public call first consults the ``fleet.transport`` schedule
    (`transport_disturb`) — a delay stalls it, a partition refuses it
    with the retryable `InjectedFault` — then passes through. Gives a
    hermetic bench/test fleet the same wire weather a real
    `RpcReplicaBackend` sees, without sockets."""

    def __init__(self, target, schedule: ChaosSchedule):
        self._target = target
        self._schedule = schedule
        self.name = f"transport-chaos+{getattr(target, 'name', '?')}"

    @property
    def inner(self):
        """Wrapper-chain hop (breaker_of / serving nesting guard)."""
        return self._target

    def __getattr__(self, name: str):
        attr = getattr(self._target, name)
        if name.startswith("_") or name == "close" or not callable(attr):
            return attr  # lifecycle/local reads never cross the wire
        schedule = self._schedule

        def over_wire(*args, **kwargs):
            transport_disturb(schedule)
            return attr(*args, **kwargs)

        return over_wire


class _ChaosProxy:
    """Attribute proxy injecting scheduled faults in front of every
    public method of `target` (the faultyReader/faultyCaller doubles,
    generalized). Non-callable attributes and private names pass
    through; `overrides` replaces whole methods for degraded-backend
    doubles (e.g. a backend without the batched committee view)."""

    def __init__(self, target, schedule: ChaosSchedule, seam_prefix: str,
                 overrides: Optional[Dict[str, Callable]] = None):
        self._target = target
        self._schedule = schedule
        self._seam_prefix = seam_prefix
        self._overrides = overrides or {}

    def __getattr__(self, name: str):
        override = self._overrides.get(name)
        if override is not None:
            return override
        attr = getattr(self._target, name)
        if name.startswith("_"):
            return attr
        schedule, seam = self._schedule, f"{self._seam_prefix}.{name}"
        if not callable(attr):
            # property-backed reads (e.g. mainchain.block_number) are
            # injectable too, but only when a rule NAMES them — plain
            # data passthroughs must not consume schedule slots
            if schedule.has_rule(seam):
                schedule.fire(seam)
            return attr

        def chaotic(*args, **kwargs):
            schedule.fire(seam)
            return attr(*args, **kwargs)

        return chaotic


def wrap(target, schedule: ChaosSchedule, seam_prefix: str,
         overrides: Optional[Dict[str, Callable]] = None):
    """Front `target` with scheduled ``<seam_prefix>.<method>`` faults."""
    return _ChaosProxy(target, schedule, seam_prefix, overrides)


def unwired_seams(schedule: ChaosSchedule,
                  wired: Tuple[str, ...]) -> List[str]:
    """Rules whose seam prefix is not in `wired`: a spec entry the
    caller never routes through an injector fires nothing, so the
    experiment silently tests less than the operator asked for — the
    caller should warn (or refuse) rather than stay quiet."""
    return sorted(seam for seam in schedule.rules
                  if seam.split(".", 1)[0] not in wired)


class ChaosSigBackend(SigBackend):
    """`SigBackend` front injecting device faults and dispatch hangs.

    ``backend.<op>`` schedule entries raise `InjectedFault` before the
    inner call; ``dispatch.<op>`` entries sleep `hang_s` seconds first
    — when this backend sits under the serving tier, that wedges the
    dispatch thread exactly like a hung device call, which is the
    watchdog's prey. A ``backend.<op>`` seam in ``mode=corrupt``
    raises nothing: scheduled calls run the real op and then silently
    perturb its result (seeded by (seed, seam, call index), so the
    same spec corrupts the same rows every run) — the silent-
    corruption failure class the soundness spot-checker exists for."""

    def __init__(self, inner: SigBackend, schedule: ChaosSchedule,
                 hang_s: float = 30.0):
        self.inner = inner
        self.schedule = schedule
        self.hang_s = hang_s
        self.name = f"chaos+{inner.name}"

    def _corrupt_result(self, op: str, out, idx: int):
        """Silently wrong, never loud: flip one row's verdict bit, or
        perturb one recovered address (valid -> near-miss bytes,
        invalid -> fabricated address). Callers skip empty batches
        before consuming a schedule slot (nothing to corrupt without
        changing the row count, which would be a LOUD shape error);
        the guard here is defensive only."""
        out = list(out)
        if not out:  # pragma: no cover - callers skip empty batches
            return out
        rng = random.Random(
            f"{self.schedule.seed}:corrupt:{op}:{idx}")
        row = rng.randrange(len(out))
        if op == "ecrecover_addresses":
            from gethsharding_tpu.utils.hexbytes import Address20

            addr = out[row]
            if addr is None:
                out[row] = Address20(rng.randbytes(20))
            else:
                flipped = bytes(addr[:-1]) + bytes([addr[-1] ^ 0x01])
                out[row] = Address20(flipped)
        else:
            out[row] = not bool(out[row])
        return out

    def _op(self, op: str, *args, **kwargs):
        if self.schedule.should_fail(f"dispatch.{op}"):
            time.sleep(self.hang_s)
        seam = f"backend.{op}"
        if self.schedule.mode_for(seam) == "corrupt":
            rows = len(args[0]) if args else 0
            if rows == 0:
                # nothing to corrupt: off the books, so the schedule's
                # injected count stays equal to results actually
                # corrupted (fault mode still raises on empty batches)
                return getattr(self.inner, op)(*args, **kwargs)
            inject, idx = self.schedule.decide(seam)
            out = getattr(self.inner, op)(*args, **kwargs)
            return self._corrupt_result(op, out, idx) if inject else out
        self.schedule.fire(seam)
        return getattr(self.inner, op)(*args, **kwargs)

    def ecrecover_addresses(self, digests, sigs65):
        return self._op("ecrecover_addresses", digests, sigs65)

    def bls_verify_aggregates(self, messages, agg_sigs, agg_pks):
        return self._op("bls_verify_aggregates", messages, agg_sigs,
                        agg_pks)

    def bls_verify_committees(self, messages, sig_rows, pk_rows,
                              pk_row_keys=None):
        return self._op("bls_verify_committees", messages, sig_rows,
                        pk_rows, pk_row_keys=pk_row_keys)

    def das_verify_samples(self, chunks, indices, proofs, roots):
        return self._op("das_verify_samples", chunks, indices, proofs,
                        roots)

    def das_verify_multiproofs(self, commitments, index_rows, eval_rows,
                               proofs, ns):
        return self._op("das_verify_multiproofs", commitments, index_rows,
                        eval_rows, proofs, ns)

    def bls_verify_committees_async(self, messages, sig_rows, pk_rows,
                                    pk_row_keys=None):
        # fire at submit time: a fault lands where the real device
        # raises (the staged launch), and a hang wedges the submitter
        if self.schedule.should_fail("dispatch.bls_verify_committees"):
            time.sleep(self.hang_s)
        seam = "backend.bls_verify_committees"
        if self.schedule.mode_for(seam) == "corrupt":
            # corruption lands at PULL time, where a silently wrong
            # device plane would materialize — the submit stays async
            inject, idx = ((False, 0) if len(messages) == 0
                           else self.schedule.decide(seam))
            inner = self.inner.bls_verify_committees_async(
                messages, sig_rows, pk_rows, pk_row_keys=pk_row_keys)
            if not inject:
                return inner
            from gethsharding_tpu.sigbackend import VerdictFuture

            return VerdictFuture(lambda: self._corrupt_result(
                "bls_verify_committees", inner.result(), idx))
        self.schedule.fire(seam)
        return self.inner.bls_verify_committees_async(
            messages, sig_rows, pk_rows, pk_row_keys=pk_row_keys)
