"""Dispatch watchdog: a hung device dispatch must fail, not hang.

The serving tier funnels every device call through ONE dispatch thread
(`serving/pipeline.PipelinedDispatcher`). That thread is a single
point of failure the reference never had: a wedged accelerator call
(driver stall, tunnel drop, chaos-injected hang) blocks the thread
forever, every queued batch behind it, and every caller parked on a
`VerdictFuture` — the notary silently stops voting.

`DispatchWatchdog` is a monitor thread over the dispatcher's in-flight
batch. When the batch's age crosses `deadline_s` it calls
`dispatcher.fail_current(DeadlineExceeded(...))`, which

- fails the stuck batch's futures (callers already handle errored
  batches per the serving contract — and a `FailoverSigBackend` above
  counts the `DeadlineExceeded` as a primary fault, feeding the
  breaker);
- hands the ready-batch queue to a FRESH dispatch thread so the next
  batch serves immediately (the stuck thread is daemon; it notices it
  was superseded when its device call finally returns and exits).

Counters: ``resilience/watchdog/timeouts`` / ``/restarts``. The
optional `on_timeout` hook is for wiring that wants the event
directly (the exception path through the failover face needs nothing).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from gethsharding_tpu import metrics
from gethsharding_tpu.perfwatch import RECORDER
from gethsharding_tpu.resilience.errors import DeadlineExceeded

log = logging.getLogger("resilience.watchdog")


class DispatchWatchdog:
    """Deadline monitor + restarter for a `PipelinedDispatcher`."""

    def __init__(self, dispatcher, deadline_s: float = 5.0,
                 poll_s: Optional[float] = None,
                 on_timeout: Optional[Callable[[], None]] = None,
                 name: str = "serving-watchdog",
                 registry: metrics.Registry = metrics.DEFAULT_REGISTRY):
        if deadline_s <= 0:
            raise ValueError("watchdog deadline must be positive")
        self.dispatcher = dispatcher
        self.deadline_s = deadline_s
        # poll fast enough that a hang is declared well inside ~1.25x
        # the deadline, slow enough to cost nothing when healthy
        self.poll_s = poll_s if poll_s is not None \
            else max(0.005, deadline_s / 4.0)
        self.on_timeout = on_timeout
        self.timeouts = 0
        self._m_timeouts = registry.counter("resilience/watchdog/timeouts")
        self._m_restarts = registry.counter("resilience/watchdog/restarts")
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - the MONITOR must outlive
                # its own failures (e.g. thread-spawn exhaustion inside
                # fail_current on a degraded host): a dead watchdog is a
                # silent return to the unmonitored hang it exists to
                # prevent
                log.exception("watchdog tick failed; monitor continues")

    def _tick(self) -> None:
        age = self.dispatcher.current_batch_age()
        if age is None or age <= self.deadline_s:
            return
        exc = DeadlineExceeded(
            f"device dispatch hung for {age:.3f}s "
            f"(deadline {self.deadline_s:.3f}s); batch abandoned "
            f"and dispatcher restarted")
        # min_age_s closes the observe-then-abandon race: if the hung
        # batch completed and a fresh one started since the age read,
        # the fresh batch's age is under the deadline and survives
        if self.dispatcher.fail_current(exc, min_age_s=self.deadline_s):
            self.timeouts += 1
            self._m_timeouts.inc()
            self._m_restarts.inc()
            # a hung dispatch is exactly what the black box exists for:
            # freeze the last-N events/spans/wire ledgers to disk
            RECORDER.trigger("watchdog_timeout", dump=True,
                             age_s=round(age, 3),
                             deadline_s=self.deadline_s)
            log.error("dispatch watchdog fired: %s", exc)
            if self.on_timeout is not None:
                try:
                    self.on_timeout()
                except Exception:  # noqa: BLE001 - hook must not kill us
                    log.exception("watchdog on_timeout hook failed")

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
