"""Consensus types: Transaction, CollationHeader, Collation.

Byte-format parity:
- Transaction mirrors `core/types/transaction.go` (geth 1.8.9 txdata):
  RLP list [AccountNonce, Price, GasLimit, Recipient, Amount, Payload, V, R, S];
  hash = keccak256(rlp(tx)).
- CollationHeader mirrors `sharding/collation.go:30-64`: RLP list
  [ShardID, ChunkRoot, Period, ProposerAddress, ProposerSignature] with
  geth's nil-pointer rule (nil -> empty string); hash = keccak256(rlp(data))
  (`collation.go:66 Hash`).
- SerializeTxToBlob / DeserializeBlobToTx mirror `collation.go:158,193`:
  per-tx RLP -> 31-byte chunking -> 1 MiB size cap.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.utils.blob import RawBlob, deserialize_blobs, serialize_blobs
from gethsharding_tpu.utils.hexbytes import Address20, Hash32
from gethsharding_tpu.utils.rlp import (
    DecodingError,
    decode_int,
    int_to_big_endian,
    rlp_decode,
    rlp_encode,
)

COLLATION_SIZE_LIMIT = 1 << 20  # 1 MiB (`sharding/collation.go:45`)


def _expect_bytes(item, name: str) -> bytes:
    """Reject list-kind where a string-kind RLP field is required
    (the reference's rlp.Stream enforces kind per field)."""
    if not isinstance(item, (bytes, bytearray)):
        raise DecodingError(f"{name}: expected RLP string, got list")
    return bytes(item)


def _expect_sized(item, name: str, size: int) -> bytes:
    data = _expect_bytes(item, name)
    if len(data) != size:
        raise DecodingError(f"{name}: expected {size} bytes, got {len(data)}")
    return data


@dataclass
class Transaction:
    """A shard transaction (phase 1: opaque payload, no shard-state execution)."""

    nonce: int = 0
    gas_price: int = 0
    gas_limit: int = 0
    to: Optional[Address20] = None  # None = contract creation (nil Recipient)
    value: int = 0
    payload: bytes = b""
    v: int = 0
    r: int = 0
    s: int = 0

    def fields(self) -> list:
        return [
            int_to_big_endian(self.nonce),
            int_to_big_endian(self.gas_price),
            int_to_big_endian(self.gas_limit),
            bytes(self.to) if self.to is not None else b"",
            int_to_big_endian(self.value),
            self.payload,
            int_to_big_endian(self.v),
            int_to_big_endian(self.r),
            int_to_big_endian(self.s),
        ]

    def encode_rlp(self) -> bytes:
        return rlp_encode(self.fields())

    @classmethod
    def decode_rlp(cls, data: bytes) -> "Transaction":
        items = rlp_decode(data)
        if not isinstance(items, list) or len(items) != 9:
            raise DecodingError("transaction must be a 9-item RLP list")
        names = ("nonce", "gas_price", "gas_limit", "to", "value",
                 "payload", "v", "r", "s")
        fields = [_expect_bytes(item, name) for item, name in zip(items, names)]
        to = None if fields[3] == b"" else Address20(
            _expect_sized(fields[3], "to", 20)
        )
        return cls(
            nonce=decode_int(fields[0]),
            gas_price=decode_int(fields[1]),
            gas_limit=decode_int(fields[2]),
            to=to,
            value=decode_int(fields[4]),
            payload=fields[5],
            v=decode_int(fields[6]),
            r=decode_int(fields[7]),
            s=decode_int(fields[8]),
        )

    def hash(self) -> Hash32:
        return Hash32(keccak256(self.encode_rlp()))

    def sig_hash(self, chain_id: Optional[int] = None) -> Hash32:
        """Signing hash: homestead (6 fields) or EIP-155 (9 fields)."""
        items = self.fields()[:6]
        if chain_id is not None:
            items += [int_to_big_endian(chain_id), b"", b""]
        return Hash32(keccak256(rlp_encode(items)))


@dataclass
class CollationHeader:
    """Header of a collation; its hash is what proposers sign and notaries vote on."""

    shard_id: Optional[int] = None
    chunk_root: Optional[Hash32] = None
    period: Optional[int] = None
    proposer_address: Optional[Address20] = None
    proposer_signature: bytes = b""

    def _data_fields(self) -> list:
        return [
            int_to_big_endian(self.shard_id) if self.shard_id is not None else b"",
            bytes(self.chunk_root) if self.chunk_root is not None else b"",
            int_to_big_endian(self.period) if self.period is not None else b"",
            bytes(self.proposer_address)
            if self.proposer_address is not None
            else b"",
            self.proposer_signature,
        ]

    def encode_rlp(self) -> bytes:
        return rlp_encode(self._data_fields())

    @classmethod
    def decode_rlp(cls, data: bytes) -> "CollationHeader":
        items = rlp_decode(data)
        if not isinstance(items, list) or len(items) != 5:
            raise DecodingError("collation header must be a 5-item RLP list")
        names = ("shard_id", "chunk_root", "period", "proposer_address",
                 "proposer_signature")
        fields = [_expect_bytes(item, name) for item, name in zip(items, names)]
        return cls(
            # integer fields decode empty as ZERO (big.Int RLP parity):
            # shard 0 / period 0 and "unset" share the empty encoding, and
            # picking None here made shard-0 headers change identity
            # across a DB round-trip (the canonical lookup key embeds
            # shard_id — a None key never matches the shard-0 write)
            shard_id=decode_int(fields[0]),
            chunk_root=Hash32(_expect_sized(fields[1], "chunk_root", 32))
            if fields[1] != b"" else None,
            period=decode_int(fields[2]),
            proposer_address=Address20(
                _expect_sized(fields[3], "proposer_address", 20)
            )
            if fields[3] != b"" else None,
            proposer_signature=fields[4],
        )

    def hash(self) -> Hash32:
        return Hash32(keccak256(self.encode_rlp()))

    def add_sig(self, sig: bytes) -> None:
        self.proposer_signature = sig


@dataclass
class Collation:
    """Collation = header + serialized body blob + deserialized transactions."""

    header: CollationHeader
    body: bytes = b""
    transactions: List[Transaction] = field(default_factory=list)

    def calculate_chunk_root(self) -> Hash32:
        from gethsharding_tpu.core.derive_sha import chunk_root

        root = Hash32(chunk_root(self.body))
        self.header.chunk_root = root
        return root

    def calculate_poc(self, salt: bytes) -> Hash32:
        from gethsharding_tpu.core.derive_sha import poc_root

        return Hash32(poc_root(self.body, salt))

    def proposer_address(self) -> Optional[Address20]:
        return self.header.proposer_address


def serialize_txs_to_blob(txs: Sequence[Transaction]) -> bytes:
    """RLP-encode each tx, then blob-chunk; enforces the 1 MiB cap."""
    blobs = [RawBlob(data=tx.encode_rlp(), skip_evm=False) for tx in txs]
    serialized = serialize_blobs(blobs)
    if len(serialized) > COLLATION_SIZE_LIMIT:
        raise ValueError(
            f"serialized body size {len(serialized)} exceeds the collation "
            f"size limit {COLLATION_SIZE_LIMIT}"
        )
    return serialized


def deserialize_blob_to_txs(body: bytes) -> List[Transaction]:
    return [Transaction.decode_rlp(blob.data) for blob in deserialize_blobs(body)]
