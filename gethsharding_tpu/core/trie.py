"""Merkle-Patricia trie (hexary), hash-compatible with the reference `trie/`.

Insert/update/get/delete plus merkle proofs (`prove`/`verify_proof`,
parity: `trie/proof.go`) and the keccak-keyed `SecureTrie` wrapper
(`trie/secure_trie.go`). Node encoding follows the Ethereum
yellow-paper / go-ethereum 1.8 rules:

- leaf/extension nodes: 2-item RLP list [hex-prefix-encoded path, value]
- branch nodes: 17-item RLP list (16 children + value)
- any node whose RLP encoding is >= 32 bytes is referenced by its keccak256
  hash; shorter nodes embed directly in the parent.

The empty trie root is keccak256(rlp(b"")) =
56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421
(`trie/trie.go` emptyRoot).

This structure is host-side (collation building / validation bookkeeping).
The TPU data-availability path hashes fixed-shape chunk batches instead; see
`gethsharding_tpu.ops.keccak_jax`.
"""

from __future__ import annotations

from typing import Dict, Optional

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.utils.rlp import rlp_encode

EMPTY_ROOT = bytes.fromhex(
    "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
)


def _to_nibbles(key: bytes) -> tuple:
    out = []
    for b in key:
        out.append(b >> 4)
        out.append(b & 0x0F)
    return tuple(out)


def hex_prefix_encode(nibbles: tuple, is_leaf: bool) -> bytes:
    """Compact (hex-prefix) encoding of a nibble path + leaf flag."""
    flag = 2 if is_leaf else 0
    if len(nibbles) % 2 == 1:
        prefixed = (flag + 1,) + nibbles
    else:
        prefixed = (flag, 0) + nibbles
    out = bytearray()
    for i in range(0, len(prefixed), 2):
        out.append((prefixed[i] << 4) | prefixed[i + 1])
    return bytes(out)


class _Node:
    __slots__ = ()


class _Leaf(_Node):
    __slots__ = ("path", "value")

    def __init__(self, path: tuple, value: bytes):
        self.path = path
        self.value = value


class _Extension(_Node):
    __slots__ = ("path", "child")

    def __init__(self, path: tuple, child: _Node):
        self.path = path
        self.child = child


class _Branch(_Node):
    __slots__ = ("children", "value")

    def __init__(self):
        self.children: list = [None] * 16
        self.value: Optional[bytes] = None


def _common_prefix_len(a: tuple, b: tuple) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class Trie:
    """Insert/update/get Merkle-Patricia trie over byte keys and values."""

    def __init__(self):
        self._root: Optional[_Node] = None

    def update(self, key: bytes, value: bytes) -> None:
        if value == b"":
            # geth semantics: updating to an empty value deletes the key
            self.delete(key)
            return
        self._root = self._insert(self._root, _to_nibbles(key), value)

    def delete(self, key: bytes) -> None:
        """Remove a key (no-op if absent), restructuring single-child
        branches back into extensions/leaves (trie/trie.go delete)."""
        self._root = self._delete(self._root, _to_nibbles(key))

    def get(self, key: bytes) -> Optional[bytes]:
        node = self._root
        path = _to_nibbles(key)
        while True:
            if node is None:
                return None
            if isinstance(node, _Leaf):
                return node.value if node.path == path else None
            if isinstance(node, _Extension):
                n = len(node.path)
                if path[:n] != node.path:
                    return None
                path = path[n:]
                node = node.child
                continue
            # branch
            if not path:
                return node.value
            node, path = node.children[path[0]], path[1:]

    def _insert(self, node: Optional[_Node], path: tuple, value: bytes) -> _Node:
        if node is None:
            return _Leaf(path, value)
        if isinstance(node, _Leaf):
            if node.path == path:
                return _Leaf(path, value)
            common = _common_prefix_len(node.path, path)
            branch = _Branch()
            old_rest, new_rest = node.path[common:], path[common:]
            if not old_rest:
                branch.value = node.value
            else:
                branch.children[old_rest[0]] = _Leaf(old_rest[1:], node.value)
            if not new_rest:
                branch.value = value
            else:
                branch.children[new_rest[0]] = _Leaf(new_rest[1:], value)
            if common:
                return _Extension(path[:common], branch)
            return branch
        if isinstance(node, _Extension):
            common = _common_prefix_len(node.path, path)
            if common == len(node.path):
                node.child = self._insert(node.child, path[common:], value)
                return node
            branch = _Branch()
            ext_rest = node.path[common:]
            child = (
                node.child
                if len(ext_rest) == 1
                else _Extension(ext_rest[1:], node.child)
            )
            branch.children[ext_rest[0]] = child
            new_rest = path[common:]
            if not new_rest:
                branch.value = value
            else:
                branch.children[new_rest[0]] = _Leaf(new_rest[1:], value)
            if common:
                return _Extension(path[:common], branch)
            return branch
        # branch
        if not path:
            node.value = value
            return node
        node.children[path[0]] = self._insert(node.children[path[0]], path[1:], value)
        return node

    def _delete(self, node: Optional[_Node], path: tuple) -> Optional[_Node]:
        if node is None:
            return None
        if isinstance(node, _Leaf):
            return None if node.path == path else node
        if isinstance(node, _Extension):
            n = len(node.path)
            if path[:n] != node.path:
                return node
            child = self._delete(node.child, path[n:])
            if child is None:
                return None
            return self._merge_extension(node.path, child)
        # branch
        if not path:
            if node.value is None:
                return node
            node.value = None
        else:
            idx = path[0]
            node.children[idx] = self._delete(node.children[idx], path[1:])
        return self._collapse_branch(node)

    def _merge_extension(self, prefix: tuple, child: _Node) -> _Node:
        """Extension over `prefix` pointing at `child`, merging nested
        extensions/leaves into one path segment."""
        if isinstance(child, _Leaf):
            return _Leaf(prefix + child.path, child.value)
        if isinstance(child, _Extension):
            return _Extension(prefix + child.path, child.child)
        return _Extension(prefix, child)

    def _collapse_branch(self, node: "_Branch") -> Optional[_Node]:
        live = [(i, c) for i, c in enumerate(node.children) if c is not None]
        if node.value is not None:
            if live:
                return node
            return _Leaf((), node.value)
        if len(live) > 1:
            return node
        if not live:
            return None
        idx, child = live[0]
        return self._merge_extension((idx,), child)

    # -- merkle proofs (trie/proof.go Prove/VerifyProof) -------------------

    def prove(self, key: bytes) -> list:
        """Ordered list of node RLP blobs from the root along `key`'s
        path — every HASH-REFERENCED node on the path (embedded sub-nodes
        travel inside their parent's blob, as in geth)."""
        proof = []
        node = self._root
        path = _to_nibbles(key)
        while node is not None:
            proof.append(rlp_encode(self._node_structure(node)))
            # advance to the next hash-referenced node on the path
            node, path = self._next_hashed(node, path)
        return proof

    def _next_hashed(self, node: _Node, path: tuple):
        """Walk within one blob (through embedded children) until the path
        needs a node that is referenced by hash; returns (node, rest)."""
        while True:
            if isinstance(node, _Leaf):
                return None, path
            if isinstance(node, _Extension):
                n = len(node.path)
                if path[:n] != node.path:
                    return None, path
                path = path[n:]
                child = node.child
            else:
                if not path:
                    return None, path
                child = node.children[path[0]]
                path = path[1:]
                if child is None:
                    return None, path
            if len(rlp_encode(self._node_structure(child))) >= 32:
                return child, path
            node = child  # embedded: keep walking inside this blob

    # -- hashing ----------------------------------------------------------

    def root_hash(self) -> bytes:
        if self._root is None:
            return EMPTY_ROOT
        # the root node is always hashed, regardless of encoded size
        return keccak256(rlp_encode(self._node_structure(self._root)))

    def _node_structure(self, node: _Node):
        if isinstance(node, _Leaf):
            return [hex_prefix_encode(node.path, True), node.value]
        if isinstance(node, _Extension):
            return [hex_prefix_encode(node.path, False), self._encode_child(node.child)]
        items = []
        for child in node.children:
            items.append(b"" if child is None else self._encode_child(child))
        items.append(node.value if node.value is not None else b"")
        return items

    def _encode_child(self, node: _Node):
        structure = self._node_structure(node)
        raw = rlp_encode(structure)
        if len(raw) >= 32:
            return keccak256(raw)
        return structure  # embedded node: nested list inside parent RLP


def verify_proof(root_hash: bytes, key: bytes, proof: list) -> Optional[bytes]:
    """Check a merkle proof against a root hash; returns the proven value,
    None for a proven ABSENCE, and raises ValueError on an invalid proof.
    Parity: `trie/proof.go VerifyProof`."""
    from gethsharding_tpu.utils.rlp import rlp_decode

    if not proof:
        if root_hash == EMPTY_ROOT:
            return None
        raise ValueError("empty proof for non-empty root")
    expected = bytes(root_hash)
    path = _to_nibbles(key)
    i = 0
    structure = None
    while True:
        if structure is None:
            if i >= len(proof):
                raise ValueError("proof exhausted before path ended")
            blob = bytes(proof[i])
            if keccak256(blob) != expected:
                raise ValueError("proof node hash mismatch")
            structure = rlp_decode(blob)
            i += 1
        if not isinstance(structure, list):
            raise ValueError("malformed proof node")
        if len(structure) == 2:
            path_seg, is_leaf = _hp_decode(structure[0])
            if is_leaf:
                if path_seg == path:
                    if i != len(proof):
                        raise ValueError("trailing proof nodes")
                    return structure[1]
                return None  # proven absence
            if path[:len(path_seg)] != path_seg:
                return None
            path = path[len(path_seg):]
            nxt = structure[1]
        elif len(structure) == 17:
            if not path:
                value = structure[16]
                return value if value != b"" else None
            nxt = structure[path[0]]
            path = path[1:]
            if nxt == b"":
                return None
        else:
            raise ValueError("malformed proof node")
        if isinstance(nxt, list):
            structure = nxt  # embedded child inside the same blob
        else:
            if len(nxt) != 32:
                raise ValueError("malformed child reference")
            expected = bytes(nxt)
            structure = None


def _hp_decode(encoded: bytes):
    """Inverse of hex_prefix_encode -> (nibbles, is_leaf)."""
    if not encoded:
        raise ValueError("empty hex-prefix encoding")
    flag = encoded[0] >> 4
    nibbles = []
    if flag & 1:
        nibbles.append(encoded[0] & 0x0F)
    for b in encoded[1:]:
        nibbles.append(b >> 4)
        nibbles.append(b & 0x0F)
    return tuple(nibbles), bool(flag & 2)


class SecureTrie:
    """Trie over keccak256(key) — the state-trie keying scheme
    (`trie/secure_trie.go`)."""

    def __init__(self):
        self._trie = Trie()

    def update(self, key: bytes, value: bytes) -> None:
        self._trie.update(keccak256(key), value)

    def get(self, key: bytes) -> Optional[bytes]:
        return self._trie.get(keccak256(key))

    def delete(self, key: bytes) -> None:
        self._trie.delete(keccak256(key))

    def prove(self, key: bytes) -> list:
        return self._trie.prove(keccak256(key))

    def root_hash(self) -> bytes:
        return self._trie.root_hash()
