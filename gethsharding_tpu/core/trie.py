"""Merkle-Patricia trie (hexary), hash-compatible with the reference `trie/`.

Only the parts the sharding data path needs: insert-only tries whose root
hash feeds `DeriveSha` (chunk roots, tx roots). Node encoding follows the
Ethereum yellow-paper / go-ethereum 1.8 rules:

- leaf/extension nodes: 2-item RLP list [hex-prefix-encoded path, value]
- branch nodes: 17-item RLP list (16 children + value)
- any node whose RLP encoding is >= 32 bytes is referenced by its keccak256
  hash; shorter nodes embed directly in the parent.

The empty trie root is keccak256(rlp(b"")) =
56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421
(`trie/trie.go` emptyRoot).

This structure is host-side (collation building / validation bookkeeping).
The TPU data-availability path hashes fixed-shape chunk batches instead; see
`gethsharding_tpu.ops.keccak_jax`.
"""

from __future__ import annotations

from typing import Dict, Optional

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.utils.rlp import rlp_encode

EMPTY_ROOT = bytes.fromhex(
    "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
)


def _to_nibbles(key: bytes) -> tuple:
    out = []
    for b in key:
        out.append(b >> 4)
        out.append(b & 0x0F)
    return tuple(out)


def hex_prefix_encode(nibbles: tuple, is_leaf: bool) -> bytes:
    """Compact (hex-prefix) encoding of a nibble path + leaf flag."""
    flag = 2 if is_leaf else 0
    if len(nibbles) % 2 == 1:
        prefixed = (flag + 1,) + nibbles
    else:
        prefixed = (flag, 0) + nibbles
    out = bytearray()
    for i in range(0, len(prefixed), 2):
        out.append((prefixed[i] << 4) | prefixed[i + 1])
    return bytes(out)


class _Node:
    __slots__ = ()


class _Leaf(_Node):
    __slots__ = ("path", "value")

    def __init__(self, path: tuple, value: bytes):
        self.path = path
        self.value = value


class _Extension(_Node):
    __slots__ = ("path", "child")

    def __init__(self, path: tuple, child: _Node):
        self.path = path
        self.child = child


class _Branch(_Node):
    __slots__ = ("children", "value")

    def __init__(self):
        self.children: list = [None] * 16
        self.value: Optional[bytes] = None


def _common_prefix_len(a: tuple, b: tuple) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class Trie:
    """Insert/update/get Merkle-Patricia trie over byte keys and values."""

    def __init__(self):
        self._root: Optional[_Node] = None

    def update(self, key: bytes, value: bytes) -> None:
        if value == b"":
            raise ValueError("deletion not supported in this trie")
        self._root = self._insert(self._root, _to_nibbles(key), value)

    def get(self, key: bytes) -> Optional[bytes]:
        node = self._root
        path = _to_nibbles(key)
        while True:
            if node is None:
                return None
            if isinstance(node, _Leaf):
                return node.value if node.path == path else None
            if isinstance(node, _Extension):
                n = len(node.path)
                if path[:n] != node.path:
                    return None
                path = path[n:]
                node = node.child
                continue
            # branch
            if not path:
                return node.value
            node, path = node.children[path[0]], path[1:]

    def _insert(self, node: Optional[_Node], path: tuple, value: bytes) -> _Node:
        if node is None:
            return _Leaf(path, value)
        if isinstance(node, _Leaf):
            if node.path == path:
                return _Leaf(path, value)
            common = _common_prefix_len(node.path, path)
            branch = _Branch()
            old_rest, new_rest = node.path[common:], path[common:]
            if not old_rest:
                branch.value = node.value
            else:
                branch.children[old_rest[0]] = _Leaf(old_rest[1:], node.value)
            if not new_rest:
                branch.value = value
            else:
                branch.children[new_rest[0]] = _Leaf(new_rest[1:], value)
            if common:
                return _Extension(path[:common], branch)
            return branch
        if isinstance(node, _Extension):
            common = _common_prefix_len(node.path, path)
            if common == len(node.path):
                node.child = self._insert(node.child, path[common:], value)
                return node
            branch = _Branch()
            ext_rest = node.path[common:]
            child = (
                node.child
                if len(ext_rest) == 1
                else _Extension(ext_rest[1:], node.child)
            )
            branch.children[ext_rest[0]] = child
            new_rest = path[common:]
            if not new_rest:
                branch.value = value
            else:
                branch.children[new_rest[0]] = _Leaf(new_rest[1:], value)
            if common:
                return _Extension(path[:common], branch)
            return branch
        # branch
        if not path:
            node.value = value
            return node
        node.children[path[0]] = self._insert(node.children[path[0]], path[1:], value)
        return node

    # -- hashing ----------------------------------------------------------

    def root_hash(self) -> bytes:
        if self._root is None:
            return EMPTY_ROOT
        # the root node is always hashed, regardless of encoded size
        return keccak256(rlp_encode(self._node_structure(self._root)))

    def _node_structure(self, node: _Node):
        if isinstance(node, _Leaf):
            return [hex_prefix_encode(node.path, True), node.value]
        if isinstance(node, _Extension):
            return [hex_prefix_encode(node.path, False), self._encode_child(node.child)]
        items = []
        for child in node.children:
            items.append(b"" if child is None else self._encode_child(child))
        items.append(node.value if node.value is not None else b"")
        return items

    def _encode_child(self, node: _Node):
        structure = self._node_structure(node)
        raw = rlp_encode(structure)
        if len(raw) >= 32:
            return keccak256(raw)
        return structure  # embedded node: nested list inside parent RLP
