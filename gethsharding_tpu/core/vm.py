"""A general EVM interpreter (byzantium rules — geth 1.8.9's fork).

Closes the one sanctioned substitution gap on record (VERDICT r3
Missing #3): phase-1 CONSENSUS replaced the EVM with the native SMC
transition system (`smc/state_machine.py` + `ops/smc_jax.py` — that
remains the consensus path), but "an arbitrary contract has no home"
— this module gives it one, at the TOOLING tier the reference serves
with `core/vm/interpreter.go:106`: the `evm` CLI runs arbitrary
bytecode, and the blob codec's `skip_evm=False` flag (the phase-2
execution intent carried by every collation) has an executor to grow
into.

Scope and fidelity:
- the byzantium OPCODE SET (no constantinople shifts/CREATE2/EXTCODEHASH),
  with yellow-paper gas: quadratic memory expansion, EIP-150 63/64 call
  gas forwarding + 2300 stipend, SSTORE 20000/5000 with the 15000
  refund, SELFDESTRUCT 24000 refund (refunds capped at gas_used/2);
- the CALL family (CALL/CALLCODE/DELEGATECALL/STATICCALL) with proper
  context rules (storage owner, msg.sender/value propagation, static
  write protection), CREATE with the rlp([sender, nonce]) address,
  REVERT + returndata buffer semantics;
- precompiles 1-8 backed by THIS framework's own crypto: ecrecover via
  `crypto/secp256k1`, sha256 via hashlib, identity, modexp, and the
  bn256 add/scalar-mul/pairing trio via `crypto/bn256` (the same curve
  stack the consensus kernels batch on TPU). ripemd160 is served when
  the host's OpenSSL still provides it, else the precompile reports
  failure (documented host gap, not silent wrong output);
- host-side scalar code by design: contract execution is control-flow-
  dependent (data-dependent jumps), the one shape that does NOT belong
  on the accelerator — exactly why phase-1 consensus replaced it with
  the fixed-shape SMC kernels.

State model: a dict of Account(balance, nonce, code, storage) — the
`StateDB` surface the `evm` tool and tests need; snapshot/revert by
deep copy at call boundaries (dev-scale, like the dev chain).
"""

from __future__ import annotations

import copy
import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.utils.rlp import rlp_encode

UINT_MAX = (1 << 256) - 1
SIGN_BIT = 1 << 255

# -- gas schedule (byzantium) ----------------------------------------------
G_ZERO = 0
G_BASE = 2
G_VERYLOW = 3
G_LOW = 5
G_MID = 8
G_HIGH = 10
G_EXTCODE = 700
G_BALANCE = 400
G_SLOAD = 200
G_JUMPDEST = 1
G_SSET = 20000
G_SRESET = 5000
R_SCLEAR = 15000
R_SELFDESTRUCT = 24000
G_SELFDESTRUCT = 5000
G_CREATE = 32000
G_CODEDEPOSIT = 200
G_CALL = 700
G_CALLVALUE = 9000
G_CALLSTIPEND = 2300
G_NEWACCOUNT = 25000
G_EXP = 10
G_EXPBYTE = 50
G_MEMORY = 3
G_COPY = 3
G_BLOCKHASH = 20
G_LOG = 375
G_LOGDATA = 8
G_LOGTOPIC = 375
G_KECCAK = 30
G_KECCAKWORD = 6
QUAD_DIVISOR = 512
MAX_CALL_DEPTH = 1024
MAX_CODE_SIZE = 24576


class VMError(Exception):
    """Exceptional halt: consumes ALL gas of the failing frame."""


class OutOfGas(VMError):
    pass


@dataclass
class Account:
    balance: int = 0
    nonce: int = 0
    code: bytes = b""
    storage: Dict[int, int] = field(default_factory=dict)

    def is_empty(self) -> bool:
        return not self.balance and not self.nonce and not self.code


class StateDB:
    """Dev-scale account state with snapshot/revert at call boundaries."""

    def __init__(self):
        self.accounts: Dict[bytes, Account] = {}

    def get(self, addr: bytes) -> Account:
        acct = self.accounts.get(addr)
        if acct is None:
            acct = self.accounts[addr] = Account()
        return acct

    def exists(self, addr: bytes) -> bool:
        acct = self.accounts.get(addr)
        return acct is not None and not acct.is_empty()

    def snapshot(self):
        return copy.deepcopy(self.accounts)

    def revert(self, snap) -> None:
        self.accounts = snap


@dataclass
class Env:
    """Block/tx context (the subset the byzantium opcodes read)."""

    origin: bytes = b"\x00" * 20
    gas_price: int = 0
    coinbase: bytes = b"\x00" * 20
    number: int = 0
    timestamp: int = 0
    difficulty: int = 0
    gas_limit: int = 10_000_000
    # number -> bytes32 (None: keccak of the number)
    blockhash_fn: Optional[object] = None

    def blockhash(self, n: int) -> bytes:
        if self.blockhash_fn is not None:
            return self.blockhash_fn(n)
        return keccak256(n.to_bytes(8, "big"))


@dataclass
class CallResult:
    success: bool
    output: bytes
    gas_left: int
    logs: List[Tuple[bytes, List[int], bytes]]


def _s256(x: int) -> int:
    """uint256 -> signed."""
    return x - (1 << 256) if x & SIGN_BIT else x


def _u256(x: int) -> int:
    return x & UINT_MAX


def _mem_words(n_bytes: int) -> int:
    return (n_bytes + 31) // 32


def _mem_cost(words: int) -> int:
    return G_MEMORY * words + words * words // QUAD_DIVISOR


class _Frame:
    """One execution frame (code, stack, memory, pc, gas)."""

    __slots__ = ("code", "stack", "memory", "pc", "gas", "jumpdests",
                 "returndata")

    def __init__(self, code: bytes, gas: int):
        self.code = code
        self.stack: List[int] = []
        self.memory = bytearray()
        self.pc = 0
        self.gas = gas
        self.returndata = b""
        # valid JUMPDESTs: positions not inside PUSH data
        dests = set()
        i = 0
        while i < len(code):
            op = code[i]
            if op == 0x5B:
                dests.add(i)
            i += (op - 0x5F) + 1 if 0x60 <= op <= 0x7F else 1
        self.jumpdests = dests

    # -- helpers -----------------------------------------------------------

    def use(self, amount: int) -> None:
        if amount > self.gas:
            raise OutOfGas(f"need {amount}, have {self.gas}")
        self.gas -= amount

    def pop(self) -> int:
        if not self.stack:
            raise VMError("stack underflow")
        return self.stack.pop()

    def push(self, v: int) -> None:
        if len(self.stack) >= 1024:
            raise VMError("stack overflow")
        self.stack.append(v & UINT_MAX)

    def expand(self, offset: int, size: int) -> None:
        """Charge + grow memory to cover [offset, offset+size)."""
        if size == 0:
            return
        if offset + size > 0x7FFFFFFF:
            raise OutOfGas("memory offset overflow")
        new_words = _mem_words(offset + size)
        old_words = _mem_words(len(self.memory))
        if new_words > old_words:
            self.use(_mem_cost(new_words) - _mem_cost(old_words))
            self.memory.extend(b"\x00" * (new_words * 32 - len(self.memory)))

    def mread(self, offset: int, size: int) -> bytes:
        self.expand(offset, size)
        return bytes(self.memory[offset:offset + size])

    def mwrite(self, offset: int, data: bytes) -> None:
        self.expand(offset, len(data))
        self.memory[offset:offset + len(data)] = data


class EVM:
    """The interpreter. One instance per top-level call/tx."""

    def __init__(self, state: Optional[StateDB] = None,
                 env: Optional[Env] = None, trace: bool = False):
        self.state = state if state is not None else StateDB()
        self.env = env if env is not None else Env()
        self.trace_enabled = trace
        self.trace: List[dict] = []
        self.logs: List[Tuple[bytes, List[int], bytes]] = []
        self._selfdestructs: set = set()
        # the tx-wide refund counter (geth's StateDB refund journal):
        # frames ADD to it, a reverting frame rolls it back, and the
        # TOP-LEVEL entry (`execute`) applies min(refund, gas_used//2)
        self.refund = 0

    # -- entry points ------------------------------------------------------

    def call(self, caller: bytes, to: bytes, value: int, data: bytes,
             gas: int, *, static: bool = False, depth: int = 0,
             code: Optional[bytes] = None,
             storage_addr: Optional[bytes] = None,
             code_addr: Optional[bytes] = None,
             transfer: bool = True) -> CallResult:
        """Message call into `to` (or explicit `code` for CALLCODE /
        DELEGATECALL, with `storage_addr` owning the touched storage).
        `transfer=False` (DELEGATECALL): `value` is only the CALLVALUE
        the callee observes — no balance moves."""
        if depth > MAX_CALL_DEPTH:
            return CallResult(False, b"", 0, [])
        snap = self.state.snapshot()
        logs_mark = len(self.logs)
        refund_mark = self.refund
        if value and transfer and not static:
            sender = self.state.get(caller)
            if sender.balance < value:
                return CallResult(False, b"", gas, [])
            sender.balance -= value
            self.state.get(to).balance += value
        run_code = self.state.get(to).code if code is None else code
        # precompiles dispatch on the CODE-SOURCE address: CALLCODE /
        # DELEGATECALL to 1..8 run the precompile too (geth checks the
        # precompile set before any code lookup)
        pre_addr = to if code_addr is None else code_addr
        pre = self._precompile(pre_addr, data, gas)
        if pre is not None:
            ok, out, gas_left = pre
            if not ok:
                self.state.revert(snap)
                del self.logs[logs_mark:]
            return CallResult(ok, out, gas_left, [])
        if not run_code:
            return CallResult(True, b"", gas, [])
        frame = _Frame(run_code, gas)
        owner = to if storage_addr is None else storage_addr
        try:
            out = self._run(frame, caller=caller, address=owner,
                            value=value, data=data, static=static,
                            depth=depth)
            return CallResult(True, out, frame.gas,
                              self.logs[logs_mark:])
        except _Revert as rev:
            self.state.revert(snap)
            del self.logs[logs_mark:]
            self.refund = refund_mark
            return CallResult(False, rev.output, frame.gas, [])
        except VMError:
            self.state.revert(snap)
            del self.logs[logs_mark:]
            self.refund = refund_mark
            return CallResult(False, b"", 0, [])

    def create(self, caller: bytes, value: int, initcode: bytes,
               gas: int, *, depth: int = 0) -> Tuple[Optional[bytes],
                                                     CallResult]:
        """CREATE: run initcode, deposit returned code. Returns
        (new_address | None, result)."""
        sender = self.state.get(caller)
        if sender.balance < value or depth > MAX_CALL_DEPTH:
            return None, CallResult(False, b"", gas, [])
        nonce = sender.nonce
        sender.nonce += 1
        new_addr = keccak256(rlp_encode([caller, nonce]))[12:]
        snap = self.state.snapshot()
        logs_mark = len(self.logs)
        refund_mark = self.refund
        sender = self.state.get(caller)
        sender.balance -= value
        acct = self.state.get(new_addr)
        acct.balance += value
        acct.nonce = 1
        frame = _Frame(initcode, gas)
        try:
            out = self._run(frame, caller=caller, address=new_addr,
                            value=value, data=b"", static=False,
                            depth=depth)
            if len(out) > MAX_CODE_SIZE:
                raise VMError("code size limit")
            frame.use(G_CODEDEPOSIT * len(out))
            self.state.get(new_addr).code = bytes(out)
            return new_addr, CallResult(True, b"", frame.gas,
                                        self.logs[logs_mark:])
        except _Revert as rev:
            self.state.revert(snap)
            del self.logs[logs_mark:]
            self.refund = refund_mark
            return None, CallResult(False, rev.output, frame.gas, [])
        except VMError:
            self.state.revert(snap)
            del self.logs[logs_mark:]
            self.refund = refund_mark
            return None, CallResult(False, b"", 0, [])

    # -- precompiles (byzantium set, backed by our own crypto) -------------

    def _precompile(self, to: bytes, data: bytes, gas: int):
        pid = int.from_bytes(to, "big")
        if not 1 <= pid <= 8:
            return None
        try:
            if pid == 1:   # ecrecover
                cost = 3000
                if gas < cost:
                    return False, b"", 0
                from gethsharding_tpu.crypto import secp256k1

                h = data[:32].ljust(32, b"\x00")
                v = int.from_bytes(data[32:64].ljust(32, b"\x00"), "big")
                r = int.from_bytes(data[64:96].ljust(32, b"\x00"), "big")
                s = int.from_bytes(data[96:128].ljust(32, b"\x00"), "big")
                out = b""
                if v in (27, 28) and 0 < r < secp256k1.N and \
                        0 < s < secp256k1.N:
                    try:
                        addr = secp256k1.ecrecover_address(
                            h, secp256k1.Signature(r=r, s=s, v=v - 27))
                        if addr is not None:
                            out = b"\x00" * 12 + bytes(addr)
                    except Exception:
                        out = b""
                return True, out, gas - cost
            if pid == 2:   # sha256
                cost = 60 + 12 * _mem_words(len(data))
                if gas < cost:
                    return False, b"", 0
                return True, hashlib.sha256(data).digest(), gas - cost
            if pid == 3:   # ripemd160 (host OpenSSL permitting)
                cost = 600 + 120 * _mem_words(len(data))
                if gas < cost:
                    return False, b"", 0
                try:
                    digest = hashlib.new("ripemd160", data).digest()
                except (ValueError, TypeError):
                    return False, b"", 0  # host lacks ripemd: loud fail
                return True, digest.rjust(32, b"\x00"), gas - cost
            if pid == 4:   # identity
                cost = 15 + 3 * _mem_words(len(data))
                if gas < cost:
                    return False, b"", 0
                return True, data, gas - cost
            if pid == 5:   # modexp (EIP-198)
                b_len = int.from_bytes(data[0:32].ljust(32, b"\x00"), "big")
                e_len = int.from_bytes(data[32:64].ljust(32, b"\x00"), "big")
                m_len = int.from_bytes(data[64:96].ljust(32, b"\x00"), "big")
                if max(b_len, e_len, m_len) > 1 << 20:
                    return False, b"", 0
                body = data[96:].ljust(b_len + e_len + m_len, b"\x00")
                base = int.from_bytes(body[:b_len], "big")
                exp = int.from_bytes(body[b_len:b_len + e_len], "big")
                mod = int.from_bytes(
                    body[b_len + e_len:b_len + e_len + m_len], "big")
                words = _mem_words(max(b_len, m_len))
                mult = (words * words if words <= 64 else
                        words * words // 4 + 96 * words - 3072
                        if words <= 1024 else
                        words * words // 16 + 480 * words - 199680)
                adj = max(1, exp.bit_length() - 1 if e_len <= 32
                          else 8 * (e_len - 32) + max(
                              0, int.from_bytes(
                                  body[b_len:b_len + 32], "big"
                              ).bit_length() - 1))
                cost = max(1, mult * adj // 20)
                if gas < cost:
                    return False, b"", 0
                out = (b"" if m_len == 0 else
                       pow(base, exp, mod).to_bytes(m_len, "big")
                       if mod else b"\x00" * m_len)
                return True, out, gas - cost
            from gethsharding_tpu.crypto import bn256 as bn

            if pid == 6:   # bn256 add
                cost = 500
                if gas < cost:
                    return False, b"", 0
                p1 = self._dec_g1(data[0:64])
                p2 = self._dec_g1(data[64:128])
                out = self._enc_g1(bn.g1_add(p1, p2))
                return True, out, gas - cost
            if pid == 7:   # bn256 scalar mul
                cost = 40000
                if gas < cost:
                    return False, b"", 0
                p1 = self._dec_g1(data[0:64])
                k = int.from_bytes(data[64:96].ljust(32, b"\x00"), "big")
                out = self._enc_g1(bn.g1_mul(k % bn.N, p1)
                                   if k % bn.N else None)
                return True, out, gas - cost
            # pid == 8: bn256 pairing check
            if len(data) % 192:
                return False, b"", 0
            pairs = len(data) // 192
            cost = 100000 + 80000 * pairs
            if gas < cost:
                return False, b"", 0
            acc = True
            g1s, g2s = [], []
            for i in range(pairs):
                chunk = data[i * 192:(i + 1) * 192]
                g1s.append(self._dec_g1(chunk[:64]))
                g2s.append(self._dec_g2(chunk[64:192]))
            ok = bn.pairing_check(
                [(p, q) for p, q in zip(g1s, g2s)
                 if p is not None and q is not None])
            acc = ok
            out = (1 if acc else 0).to_bytes(32, "big")
            return True, out, gas - cost
        except ValueError:
            return False, b"", 0  # malformed points: precompile failure

    @staticmethod
    def _dec_g1(raw: bytes):
        raw = raw.ljust(64, b"\x00")
        x = int.from_bytes(raw[:32], "big")
        y = int.from_bytes(raw[32:64], "big")
        if x == 0 and y == 0:
            return None  # infinity
        from gethsharding_tpu.crypto import bn256 as bn

        if not bn.g1_is_on_curve((x, y)):
            raise ValueError("g1 point not on curve")
        return (x, y)

    @staticmethod
    def _enc_g1(p) -> bytes:
        if p is None:
            return b"\x00" * 64
        return p[0].to_bytes(32, "big") + p[1].to_bytes(32, "big")

    @staticmethod
    def _dec_g2(raw: bytes):
        from gethsharding_tpu.crypto import bn256 as bn

        raw = raw.ljust(128, b"\x00")
        # EVM G2 encoding: (x_imag, x_real, y_imag, y_real)
        xb = int.from_bytes(raw[0:32], "big")
        xa = int.from_bytes(raw[32:64], "big")
        yb = int.from_bytes(raw[64:96], "big")
        ya = int.from_bytes(raw[96:128], "big")
        if xa == xb == ya == yb == 0:
            return None
        q = (bn.Fp2(xa, xb), bn.Fp2(ya, yb))
        if not bn.g2_is_on_curve(q):
            raise ValueError("g2 point not on curve")
        return q

    # -- the dispatch loop -------------------------------------------------

    def _run(self, f: _Frame, *, caller: bytes, address: bytes,
             value: int, data: bytes, static: bool, depth: int) -> bytes:
        env = self.env
        state = self.state
        while True:
            if f.pc >= len(f.code):
                return b""
            op = f.code[f.pc]
            if self.trace_enabled:
                self.trace.append({"pc": f.pc, "op": op, "gas": f.gas,
                                   "stack": list(f.stack[-4:])})
            f.pc += 1

            # PUSH1..PUSH32
            if 0x60 <= op <= 0x7F:
                n = op - 0x5F
                f.use(G_VERYLOW)
                f.push(int.from_bytes(f.code[f.pc:f.pc + n], "big"))
                f.pc += n
                continue
            # DUP1..DUP16
            if 0x80 <= op <= 0x8F:
                n = op - 0x7F
                f.use(G_VERYLOW)
                if len(f.stack) < n:
                    raise VMError("stack underflow")
                f.push(f.stack[-n])
                continue
            # SWAP1..SWAP16
            if 0x90 <= op <= 0x9F:
                n = op - 0x8F
                f.use(G_VERYLOW)
                if len(f.stack) < n + 1:
                    raise VMError("stack underflow")
                f.stack[-1], f.stack[-n - 1] = f.stack[-n - 1], f.stack[-1]
                continue

            if op == 0x00:      # STOP
                return b""
            if op == 0x01:      # ADD
                f.use(G_VERYLOW)
                f.push(f.pop() + f.pop())
            elif op == 0x02:    # MUL
                f.use(G_LOW)
                f.push(f.pop() * f.pop())
            elif op == 0x03:    # SUB
                f.use(G_VERYLOW)
                a, b = f.pop(), f.pop()
                f.push(a - b)
            elif op == 0x04:    # DIV
                f.use(G_LOW)
                a, b = f.pop(), f.pop()
                f.push(a // b if b else 0)
            elif op == 0x05:    # SDIV
                f.use(G_LOW)
                a, b = _s256(f.pop()), _s256(f.pop())
                f.push(0 if b == 0 else
                       _u256(-(-a // b) if (a < 0) != (b < 0) and a % b
                             else a // b))
            elif op == 0x06:    # MOD
                f.use(G_LOW)
                a, b = f.pop(), f.pop()
                f.push(a % b if b else 0)
            elif op == 0x07:    # SMOD
                f.use(G_LOW)
                a, b = _s256(f.pop()), _s256(f.pop())
                f.push(0 if b == 0 else
                       _u256((abs(a) % abs(b)) * (1 if a >= 0 else -1)))
            elif op == 0x08:    # ADDMOD
                f.use(G_MID)
                a, b, n = f.pop(), f.pop(), f.pop()
                f.push((a + b) % n if n else 0)
            elif op == 0x09:    # MULMOD
                f.use(G_MID)
                a, b, n = f.pop(), f.pop(), f.pop()
                f.push((a * b) % n if n else 0)
            elif op == 0x0A:    # EXP
                base, exp = f.pop(), f.pop()
                f.use(G_EXP + G_EXPBYTE * ((exp.bit_length() + 7) // 8))
                f.push(pow(base, exp, 1 << 256))
            elif op == 0x0B:    # SIGNEXTEND
                f.use(G_LOW)
                k, v = f.pop(), f.pop()
                if k < 31:
                    bit = 8 * k + 7
                    mask = (1 << (bit + 1)) - 1
                    v = (v & mask) | (UINT_MAX ^ mask if v & (1 << bit)
                                      else 0)
                f.push(v)
            elif op == 0x10:    # LT
                f.use(G_VERYLOW)
                f.push(1 if f.pop() < f.pop() else 0)
            elif op == 0x11:    # GT
                f.use(G_VERYLOW)
                f.push(1 if f.pop() > f.pop() else 0)
            elif op == 0x12:    # SLT
                f.use(G_VERYLOW)
                f.push(1 if _s256(f.pop()) < _s256(f.pop()) else 0)
            elif op == 0x13:    # SGT
                f.use(G_VERYLOW)
                f.push(1 if _s256(f.pop()) > _s256(f.pop()) else 0)
            elif op == 0x14:    # EQ
                f.use(G_VERYLOW)
                f.push(1 if f.pop() == f.pop() else 0)
            elif op == 0x15:    # ISZERO
                f.use(G_VERYLOW)
                f.push(1 if f.pop() == 0 else 0)
            elif op == 0x16:    # AND
                f.use(G_VERYLOW)
                f.push(f.pop() & f.pop())
            elif op == 0x17:    # OR
                f.use(G_VERYLOW)
                f.push(f.pop() | f.pop())
            elif op == 0x18:    # XOR
                f.use(G_VERYLOW)
                f.push(f.pop() ^ f.pop())
            elif op == 0x19:    # NOT
                f.use(G_VERYLOW)
                f.push(UINT_MAX ^ f.pop())
            elif op == 0x1A:    # BYTE
                f.use(G_VERYLOW)
                i, v = f.pop(), f.pop()
                f.push((v >> (8 * (31 - i))) & 0xFF if i < 32 else 0)
            elif op == 0x20:    # KECCAK256
                offset, size = f.pop(), f.pop()
                f.use(G_KECCAK + G_KECCAKWORD * _mem_words(size))
                f.push(int.from_bytes(keccak256(f.mread(offset, size)),
                                      "big"))
            elif op == 0x30:    # ADDRESS
                f.use(G_BASE)
                f.push(int.from_bytes(address, "big"))
            elif op == 0x31:    # BALANCE
                f.use(G_BALANCE)
                f.push(state.get(f.pop().to_bytes(32, "big")[12:]).balance)
            elif op == 0x32:    # ORIGIN
                f.use(G_BASE)
                f.push(int.from_bytes(env.origin, "big"))
            elif op == 0x33:    # CALLER
                f.use(G_BASE)
                f.push(int.from_bytes(caller, "big"))
            elif op == 0x34:    # CALLVALUE
                f.use(G_BASE)
                f.push(value)
            elif op == 0x35:    # CALLDATALOAD
                f.use(G_VERYLOW)
                i = f.pop()
                f.push(int.from_bytes(data[i:i + 32].ljust(32, b"\x00"),
                                      "big") if i < len(data) else 0)
            elif op == 0x36:    # CALLDATASIZE
                f.use(G_BASE)
                f.push(len(data))
            elif op == 0x37:    # CALLDATACOPY
                dst, src, size = f.pop(), f.pop(), f.pop()
                f.use(G_VERYLOW + G_COPY * _mem_words(size))
                chunk = data[src:src + size] if src < len(data) else b""
                f.mwrite(dst, chunk.ljust(size, b"\x00"))
            elif op == 0x38:    # CODESIZE
                f.use(G_BASE)
                f.push(len(f.code))
            elif op == 0x39:    # CODECOPY
                dst, src, size = f.pop(), f.pop(), f.pop()
                f.use(G_VERYLOW + G_COPY * _mem_words(size))
                chunk = f.code[src:src + size] if src < len(f.code) else b""
                f.mwrite(dst, chunk.ljust(size, b"\x00"))
            elif op == 0x3A:    # GASPRICE
                f.use(G_BASE)
                f.push(env.gas_price)
            elif op == 0x3B:    # EXTCODESIZE
                f.use(G_EXTCODE)
                f.push(len(state.get(
                    f.pop().to_bytes(32, "big")[12:]).code))
            elif op == 0x3C:    # EXTCODECOPY
                addr = f.pop().to_bytes(32, "big")[12:]
                dst, src, size = f.pop(), f.pop(), f.pop()
                f.use(G_EXTCODE + G_COPY * _mem_words(size))
                code = state.get(addr).code
                chunk = code[src:src + size] if src < len(code) else b""
                f.mwrite(dst, chunk.ljust(size, b"\x00"))
            elif op == 0x3D:    # RETURNDATASIZE
                f.use(G_BASE)
                f.push(len(f.returndata))
            elif op == 0x3E:    # RETURNDATACOPY
                dst, src, size = f.pop(), f.pop(), f.pop()
                f.use(G_VERYLOW + G_COPY * _mem_words(size))
                if src + size > len(f.returndata):
                    raise VMError("returndata out of bounds")
                f.mwrite(dst, f.returndata[src:src + size])
            elif op == 0x40:    # BLOCKHASH
                f.use(G_BLOCKHASH)
                n = f.pop()
                f.push(int.from_bytes(env.blockhash(n), "big")
                       if env.number - 256 <= n < env.number else 0)
            elif op == 0x41:    # COINBASE
                f.use(G_BASE)
                f.push(int.from_bytes(env.coinbase, "big"))
            elif op == 0x42:    # TIMESTAMP
                f.use(G_BASE)
                f.push(env.timestamp)
            elif op == 0x43:    # NUMBER
                f.use(G_BASE)
                f.push(env.number)
            elif op == 0x44:    # DIFFICULTY
                f.use(G_BASE)
                f.push(env.difficulty)
            elif op == 0x45:    # GASLIMIT
                f.use(G_BASE)
                f.push(env.gas_limit)
            elif op == 0x50:    # POP
                f.use(G_BASE)
                f.pop()
            elif op == 0x51:    # MLOAD
                f.use(G_VERYLOW)
                f.push(int.from_bytes(f.mread(f.pop(), 32), "big"))
            elif op == 0x52:    # MSTORE
                f.use(G_VERYLOW)
                offset, v = f.pop(), f.pop()
                f.mwrite(offset, v.to_bytes(32, "big"))
            elif op == 0x53:    # MSTORE8
                f.use(G_VERYLOW)
                offset, v = f.pop(), f.pop()
                f.mwrite(offset, bytes([v & 0xFF]))
            elif op == 0x54:    # SLOAD
                f.use(G_SLOAD)
                f.push(state.get(address).storage.get(f.pop(), 0))
            elif op == 0x55:    # SSTORE
                if static:
                    raise VMError("SSTORE in static context")
                key, v = f.pop(), f.pop()
                storage = state.get(address).storage
                old = storage.get(key, 0)
                if old == 0 and v != 0:
                    f.use(G_SSET)
                else:
                    f.use(G_SRESET)
                    if old != 0 and v == 0:
                        self.refund += R_SCLEAR
                if v:
                    storage[key] = v
                else:
                    storage.pop(key, None)
            elif op == 0x56:    # JUMP
                f.use(G_MID)
                dest = f.pop()
                if dest not in f.jumpdests:
                    raise VMError("invalid jump destination")
                f.pc = dest
            elif op == 0x57:    # JUMPI
                f.use(G_HIGH)
                dest, cond = f.pop(), f.pop()
                if cond:
                    if dest not in f.jumpdests:
                        raise VMError("invalid jump destination")
                    f.pc = dest
            elif op == 0x58:    # PC
                f.use(G_BASE)
                f.push(f.pc - 1)
            elif op == 0x59:    # MSIZE
                f.use(G_BASE)
                f.push(len(f.memory))
            elif op == 0x5A:    # GAS
                f.use(G_BASE)
                f.push(f.gas)
            elif op == 0x5B:    # JUMPDEST
                f.use(G_JUMPDEST)
            elif 0xA0 <= op <= 0xA4:  # LOG0..LOG4
                if static:
                    raise VMError("LOG in static context")
                n_topics = op - 0xA0
                offset, size = f.pop(), f.pop()
                topics = [f.pop() for _ in range(n_topics)]
                f.use(G_LOG + G_LOGTOPIC * n_topics + G_LOGDATA * size)
                self.logs.append((address, topics, f.mread(offset, size)))
            elif op == 0xF0:    # CREATE
                if static:
                    raise VMError("CREATE in static context")
                cvalue, offset, size = f.pop(), f.pop(), f.pop()
                initcode = f.mread(offset, size)
                f.use(G_CREATE)
                child_gas = f.gas - f.gas // 64
                f.gas -= child_gas
                addr, res = self.create(address, cvalue, initcode,
                                        child_gas, depth=depth + 1)
                f.gas += res.gas_left
                f.returndata = res.output if not res.success else b""
                f.push(int.from_bytes(addr, "big") if addr else 0)
            elif op in (0xF1, 0xF2, 0xF4, 0xFA):  # CALL family
                f.use(G_CALL)
                cgas = f.pop()
                to = f.pop().to_bytes(32, "big")[12:]
                if op in (0xF1, 0xF2):
                    cvalue = f.pop()
                else:
                    cvalue = 0
                in_off, in_size = f.pop(), f.pop()
                out_off, out_size = f.pop(), f.pop()
                if op == 0xF1 and static and cvalue:
                    raise VMError("value CALL in static context")
                indata = f.mread(in_off, in_size)
                f.expand(out_off, out_size)
                extra = 0
                if cvalue:
                    extra += G_CALLVALUE
                    if op == 0xF1 and not self.state.exists(to):
                        extra += G_NEWACCOUNT
                f.use(extra)
                avail = f.gas - f.gas // 64
                child_gas = min(cgas, avail)
                f.gas -= child_gas
                if cvalue:
                    child_gas += G_CALLSTIPEND
                if op == 0xF1:      # CALL
                    res = self.call(address, to, cvalue, indata, child_gas,
                                    static=static, depth=depth + 1)
                elif op == 0xF2:    # CALLCODE: their code, OUR storage
                    res = self.call(address, address, cvalue, indata,
                                    child_gas, static=static,
                                    depth=depth + 1,
                                    code=state.get(to).code,
                                    code_addr=to)
                elif op == 0xF4:    # DELEGATECALL: caller/value inherited,
                    # NO balance transfer (the value is observational)
                    res = self.call(caller, address, value, indata,
                                    child_gas, static=static,
                                    depth=depth + 1,
                                    code=state.get(to).code,
                                    storage_addr=address,
                                    code_addr=to,
                                    transfer=False)
                else:               # STATICCALL
                    res = self.call(address, to, 0, indata, child_gas,
                                    static=True, depth=depth + 1)
                f.gas += res.gas_left
                f.returndata = res.output
                # copy min(out_size, len(output)) bytes; the rest of
                # the out region is NOT zero-filled (EVM semantics)
                f.mwrite(out_off, res.output[:out_size])
                f.push(1 if res.success else 0)
            elif op == 0xF3:    # RETURN
                offset, size = f.pop(), f.pop()
                return f.mread(offset, size)
            elif op == 0xFD:    # REVERT
                offset, size = f.pop(), f.pop()
                raise _Revert(f.mread(offset, size))
            elif op == 0xFF:    # SELFDESTRUCT
                if static:
                    raise VMError("SELFDESTRUCT in static context")
                heir_int = f.pop()
                heir = heir_int.to_bytes(32, "big")[12:]
                acct = state.get(address)
                cost = G_SELFDESTRUCT
                if acct.balance and not state.exists(heir):
                    cost += G_NEWACCOUNT  # EIP-161 account-creation charge
                f.use(cost)
                if address not in self._selfdestructs:
                    self._selfdestructs.add(address)
                    self.refund += R_SELFDESTRUCT
                state.get(heir).balance += acct.balance
                acct.balance = 0
                acct.code = b""
                acct.storage = {}
                return b""
            elif op == 0xFE:    # INVALID
                raise VMError("designated invalid opcode")
            else:
                raise VMError(f"unknown opcode 0x{op:02x}")


class _Revert(Exception):
    def __init__(self, output: bytes):
        self.output = output


def execute(code: bytes, *, data: bytes = b"", gas: int = 10_000_000,
            value: int = 0, state: Optional[StateDB] = None,
            env: Optional[Env] = None, caller: bytes = b"\xca" * 20,
            address: bytes = b"\xc0" * 20,
            trace: bool = False) -> Tuple[CallResult, EVM]:
    """Run raw bytecode at `address` (the `evm run` entry): installs the
    code, executes a message call against it, returns (result, vm)."""
    vm = EVM(state=state, env=env, trace=trace)
    vm.state.get(address).code = bytes(code)
    res = vm.call(caller, address, value, data, gas)
    if res.success and vm.refund:
        # the tx-boundary refund rule: min(refund, gas_used // 2)
        used = gas - res.gas_left
        res = CallResult(res.success, res.output,
                         res.gas_left + min(vm.refund, used // 2),
                         res.logs)
    return res, vm
