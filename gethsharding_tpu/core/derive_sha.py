"""DeriveSha: merklize an indexed list into a trie root.

Parity with `core/types/derive_sha.go:32`: build a trie mapping
rlp(uint index) -> item-RLP, return the root hash. The collation chunk root
(`sharding/collation.go:115 CalculateChunkRoot`) applies this to the body
*bytes* (the reference's `Chunks` wrapper treats each byte as a list entry —
`collation.go:210-220` Len/GetRlp operate per byte).
"""

from __future__ import annotations

from typing import Sequence

from gethsharding_tpu.core.trie import Trie, EMPTY_ROOT
from gethsharding_tpu.utils.rlp import rlp_encode, int_to_big_endian


def derive_sha(items: Sequence[bytes]) -> bytes:
    """Root hash over rlp(index) -> item (items are already RLP-encoded).

    Large lists go through the native bulk MPT builder (`native/mpt.c` —
    the scalability answer to per-byte chunk roots over 1 MiB bodies);
    the Python trie is the fallback and differential twin."""
    if not items:
        return EMPTY_ROOT
    keys = [rlp_encode(int_to_big_endian(index))
            for index in range(len(items))]
    if len(items) >= 64:
        from gethsharding_tpu import native

        root = native.mpt_root(keys, list(items))
        if root is not None:
            return root
    trie = Trie()
    for key, item in zip(keys, items):
        trie.update(key, item)
    return trie.root_hash()


def chunk_root(body: bytes) -> bytes:
    """Chunk root of a serialized collation body (per-byte DeriveSha).

    Mirrors `Collation.CalculateChunkRoot` -> `types.DeriveSha(Chunks(body))`
    where Chunks.GetRlp(i) RLP-encodes the single byte body[i] as a *uint*
    (Go's `rlp.EncodeToBytes(byte)` hits writeUint), so 0x00 encodes as 0x80,
    not as a 1-byte string.
    """
    return derive_sha([rlp_encode(int(b)) for b in body])


def poc_root(body: bytes, salt: bytes) -> bytes:
    """Proof-of-custody root: salt interleaved before every body byte.

    Mirrors `Collation.CalculatePOC` (`sharding/collation.go:124-138`),
    including the empty-body case where the POC is derived over the salt
    alone.
    """
    if len(body) == 0:
        salted = salt
    else:
        out = bytearray()
        for b in body:
            out += salt
            out.append(b)
        salted = bytes(out)
    return chunk_root(bytes(salted))


# -- on-demand chunk proofs (the les/light ODR building block) -------------

from collections import OrderedDict as _OrderedDict
from threading import Lock as _Lock

_PROOF_TRIE_CACHE: "_OrderedDict" = _OrderedDict()
_PROOF_TRIE_LOCK = _Lock()  # serving threads of several nodes share this


def _body_trie(body: bytes):
    """The per-byte DeriveSha trie for a body, LRU-cached by content
    hash: a light client samples MANY indices of the SAME root, so the
    trie builds once per body. Callers that serve UNTRUSTED requests
    must bound body size (Syncer.PROOF_BODY_CAP) — a Python trie build
    is O(len(body)) and the LRU can be thrashed across roots."""
    from gethsharding_tpu.core.trie import Trie
    from gethsharding_tpu.crypto.keccak import keccak256

    key = keccak256(body)
    with _PROOF_TRIE_LOCK:
        cached = _PROOF_TRIE_CACHE.get(key)
        if cached is not None:
            _PROOF_TRIE_CACHE.move_to_end(key)
            return cached
    trie = Trie()
    for index, byte in enumerate(body):
        trie.update(rlp_encode(int_to_big_endian(index)),
                    rlp_encode(int(byte)))
    with _PROOF_TRIE_LOCK:
        _PROOF_TRIE_CACHE[key] = trie
        while len(_PROOF_TRIE_CACHE) > 4:
            _PROOF_TRIE_CACHE.popitem(last=False)
    return trie


def chunk_proof(body: bytes, index: int) -> list:
    """Merkle proof for byte `index` of `body` under its chunk root
    (`trie/proof.go Prove` over the DeriveSha trie). Indices >= len
    yield a proof of ABSENCE — how a light client pins the body
    length without downloading the body."""
    if index < 0:
        raise ValueError(f"negative index {index}")
    return _body_trie(body).prove(rlp_encode(int_to_big_endian(index)))


def verify_chunk(root: bytes, index: int, proof):
    """Check a chunk proof against an SMC-anchored chunk root; returns
    the proven byte value, or None for a PROVEN absence (index outside
    the body). Raises ValueError on an invalid proof
    (`trie/proof.go VerifyProof`)."""
    from gethsharding_tpu.core.trie import verify_proof
    from gethsharding_tpu.utils.rlp import big_endian_to_int, rlp_decode

    value = verify_proof(bytes(root), rlp_encode(int_to_big_endian(index)),
                         list(proof))
    if value is None:
        return None
    return big_endian_to_int(rlp_decode(value))
