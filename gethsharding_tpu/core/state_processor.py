"""Phase-1 shard state replay: the deterministic transition engine.

Parity: `core/state_processor.go:56-88` (Process / ApplyTransaction) and
`core/state_transition.go:131,183` (preCheck -> buyGas -> intrinsic gas ->
value transfer), scoped to phase-1 semantics — nonce/balance/intrinsic-gas
accounting with sender recovery (`core/types/transaction_signing.go`), no
EVM execution (the sharding phase-1 contract: "no state execution on
shards", sharding/README.md). Contract creation (to=None) is out of
phase-1 scope and rejected.

Check order mirrors geth's TransitionDb exactly so acceptance statuses are
bit-compatible: (1) sender recovery, (2) nonce equality, (3) buy gas
(balance >= gas_limit*gas_price), (4) intrinsic gas <= gas_limit,
(5) value transfer (post-buy balance >= value). Any failure rejects the
whole transaction with no state change (phase-1 has no partial execution,
so a failed tx burns nothing).

Two state commitments:

- `ShardState.root` — keccak256 over the accounts in ascending address
  order, each row addr(20) || nonce_be(8) || balance_be(32): a flat,
  fixed-shape integrity check the batched device kernel
  (`ops/replay_jax.py`) reproduces byte-identically on device.
- `ShardState.trie_root` — the CANONICAL secure-MPT state root
  (`core/state/statedb.go:562` IntermediateRoot parity): value
  RLP([nonce, balance, storageRoot, codeHash]) keyed by
  keccak256(address), empty accounts absent (the EIP-158 delete-empty
  rule geth applies at finalize), so a Go node replaying the same
  transactions recomputes this exact hash.

This scalar engine is the differential-testing twin of the vmapped device
replay (BASELINE.md config 4).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.utils.hexbytes import Address20, Hash32

# gas cost model (params/protocol_params.go, geth 1.8 / homestead)
GAS_TX = 21000
GAS_TXDATA_ZERO = 4
GAS_TXDATA_NONZERO = 68

MAX_U256 = (1 << 256) - 1


def intrinsic_gas(payload: bytes) -> int:
    """TxGas + per-byte data gas (state_transition.go IntrinsicGas)."""
    nonzero = sum(1 for b in payload if b)
    return (GAS_TX + GAS_TXDATA_NONZERO * nonzero
            + GAS_TXDATA_ZERO * (len(payload) - nonzero))


def recover_sender(tx: Transaction) -> Optional[Address20]:
    """Homestead sender recovery: v = 27 + parity over sig_hash."""
    if tx.v not in (27, 28):
        return None
    try:
        sig = secp256k1.Signature(r=tx.r, s=tx.s, v=tx.v - 27)
        return secp256k1.ecrecover_address(bytes(tx.sig_hash()), sig)
    except (ValueError, AssertionError):
        return None


def sign_transaction(tx: Transaction, priv: int) -> Transaction:
    """Sign in place of the keystore path (homestead v = 27 + parity)."""
    sig = secp256k1.sign(bytes(tx.sig_hash()), priv)
    return Transaction(
        nonce=tx.nonce, gas_price=tx.gas_price, gas_limit=tx.gas_limit,
        to=tx.to, value=tx.value, payload=tx.payload,
        v=27 + sig.v, r=sig.r, s=sig.s,
    )


@dataclass
class AccountState:
    nonce: int = 0
    balance: int = 0


@dataclass
class Receipt:
    status: int              # 1 = applied, 0 = rejected (no state change)
    gas_used: int
    sender: Optional[Address20]


class ShardState:
    """Flat account states with a canonical keccak commitment."""

    def __init__(self, accounts: Optional[Dict[Address20, AccountState]] = None):
        self.accounts: Dict[Address20, AccountState] = dict(accounts or {})

    def get(self, address: Address20) -> AccountState:
        account = self.accounts.get(address)
        if account is None:
            account = AccountState()
            self.accounts[address] = account
        return account

    def root(self) -> Hash32:
        blob = b"".join(
            bytes(addr) + acct.nonce.to_bytes(8, "big")
            + acct.balance.to_bytes(32, "big")
            for addr, acct in sorted(self.accounts.items(),
                                     key=lambda kv: bytes(kv[0]))
        )
        return Hash32(keccak256(blob))

    def trie_root(self) -> Hash32:
        """Canonical secure-MPT state root (see module docstring)."""
        return state_trie_root(self.accounts)


EMPTY_CODE_HASH = keccak256(b"")  # no shard account carries code in phase 1


def account_rlp(nonce: int, balance: int) -> bytes:
    """The state-trie account value: RLP([nonce, balance, storageRoot,
    codeHash]) with the empty storage root and empty code hash
    (`core/state/state_object.go` Account; phase 1 has no shard-side
    storage or code)."""
    from gethsharding_tpu.core.trie import EMPTY_ROOT
    from gethsharding_tpu.utils.rlp import rlp_encode

    return rlp_encode([nonce, balance, EMPTY_ROOT, EMPTY_CODE_HASH])


def state_trie_root(accounts: Dict[Address20, AccountState]) -> Hash32:
    """Secure-MPT root over non-empty accounts — the commitment a geth
    node computes at `statedb.go:562`. Bulk native build
    (`native/mpt.c`, 32-byte keccak keys) when available; the Python
    SecureTrie is the fallback and differential twin."""
    from gethsharding_tpu.core.trie import EMPTY_ROOT, Trie

    items = sorted(
        (keccak256(bytes(addr)), account_rlp(acct.nonce, acct.balance))
        for addr, acct in accounts.items()
        if acct.nonce or acct.balance)
    if not items:
        return Hash32(EMPTY_ROOT)
    from gethsharding_tpu import native

    root = native.mpt_root([k for k, _ in items], [v for _, v in items])
    if root is not None:
        return Hash32(root)
    trie = Trie()  # keys are pre-hashed: plain trie == SecureTrie here
    for key, value in items:
        trie.update(key, value)
    return Hash32(trie.root_hash())


def apply_transaction(state: ShardState, tx: Transaction,
                      coinbase: Address20) -> Receipt:
    """One phase-1 state transition (ApplyTransaction parity, see module
    docstring for the check order)."""
    sender_addr = recover_sender(tx)
    if sender_addr is None or tx.to is None:
        return Receipt(status=0, gas_used=0, sender=sender_addr)
    sender = state.get(sender_addr)
    if tx.nonce != sender.nonce:
        return Receipt(status=0, gas_used=0, sender=sender_addr)
    gas_cost = tx.gas_limit * tx.gas_price
    if sender.balance < gas_cost:
        return Receipt(status=0, gas_used=0, sender=sender_addr)
    gas = intrinsic_gas(tx.payload)
    if gas > tx.gas_limit:
        return Receipt(status=0, gas_used=0, sender=sender_addr)
    if sender.balance - gas_cost < tx.value:
        return Receipt(status=0, gas_used=0, sender=sender_addr)

    # apply: nonce bump, fee to the coinbase (unused gas refunds net out:
    # phase-1 uses exactly the intrinsic gas), value transfer
    sender.nonce += 1
    fee = gas * tx.gas_price
    sender.balance -= fee + tx.value
    state.get(tx.to).balance = (state.get(tx.to).balance + tx.value) & MAX_U256
    state.get(coinbase).balance = (state.get(coinbase).balance + fee) & MAX_U256
    return Receipt(status=1, gas_used=gas, sender=sender_addr)


def process(state: ShardState, txs: Sequence[Transaction],
            coinbase: Address20) -> List[Receipt]:
    """Replay a collation's transactions in order (Process parity)."""
    return [apply_transaction(state, tx, coinbase) for tx in txs]


def replay_account_table(txs: Sequence[Transaction],
                         genesis_addrs,
                         coinbase: Address20) -> List[Address20]:
    """The fixed account table a replay operates over: genesis accounts ∪
    every touched address, ascending by bytes. ONE definition shared by
    the device marshalling (`ops/replay_jax.build_replay_inputs`) and the
    host fold-back — the row order IS the account identity."""
    addrs = {bytes(a): a for a in genesis_addrs}
    for addr in touched_addresses(txs, coinbase):
        addrs.setdefault(bytes(addr), addr)
    return [addrs[k] for k in sorted(addrs)]


def touched_addresses(txs: Sequence[Transaction],
                      coinbase: Address20) -> List[Address20]:
    """Every address a replay can touch, deduplicated, sorted — the fixed
    account table the device kernel operates over."""
    seen = {bytes(coinbase): coinbase}
    for tx in txs:
        sender = recover_sender(tx)
        if sender is not None:
            seen.setdefault(bytes(sender), sender)
        if tx.to is not None:
            seen.setdefault(bytes(tx.to), tx.to)
    return [seen[k] for k in sorted(seen)]
