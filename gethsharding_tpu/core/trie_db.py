"""Trie node database: persistence + ref-counted GC + by-hash sync.

The analog of the reference's `trie/database.go` (node store with
reference counting so dropped roots garbage-collect their unshared
nodes) and `trie/sync.go` (pull a trie by node hash from a remote
source), behind the framework's KV seam (`db/kv.py` — memory or
SQLite):

- `commit(trie)` persists every hash-referenced node of a `Trie` and
  takes a reference on its root. Structure sharing is free: an
  unchanged subtree hashes to the same node key, so committing
  successive versions of a state trie stores only the delta (exactly
  geth's content-addressed node model).
- `dereference(root)` drops a root; nodes whose reference count reaches
  zero are deleted, cascading into their children (trie/database.go
  Dereference).
- `load(root)` reconstructs a `Trie` object from stored nodes.
- `TrieSync` pulls a trie into the database from any `fetch(hash) ->
  blob` source (a peer protocol, another database), verifying every
  blob against its hash — the future shard-state-sync building block.

Key scheme: ``trie-node:<hash32>`` -> node RLP, ``trie-ref:<hash32>``
-> big-endian reference count. Only hash-referenced (>= 32 byte) nodes
are stored; embedded children travel inside their parent's blob, as in
the reference.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from gethsharding_tpu.core.trie import (
    EMPTY_ROOT, Trie, _Branch, _Extension, _hp_decode, _Leaf)
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.db.kv import KVStore, MemoryKV
from gethsharding_tpu.utils.rlp import rlp_decode, rlp_encode

_NODE = b"trie-node:"
_REF = b"trie-ref:"

_CODEC = Trie()  # stateless encoder: _node_structure reads no trie state


def _child_hashes(structure) -> List[bytes]:
    """Hash references inside one decoded node structure (recursing
    through embedded children, which live inside this blob)."""
    refs: List[bytes] = []
    if not isinstance(structure, list):
        return refs
    if len(structure) == 2:
        _, is_leaf = _hp_decode(structure[0])
        if not is_leaf:
            child = structure[1]
            if isinstance(child, list):
                refs.extend(_child_hashes(child))
            elif len(child) == 32:
                refs.append(bytes(child))
    elif len(structure) == 17:
        for child in structure[:16]:
            if isinstance(child, list):
                refs.extend(_child_hashes(child))
            elif child != b"" and len(child) == 32:
                refs.append(bytes(child))
    return refs


class TrieDatabase:
    """Ref-counted trie node store over a KV engine."""

    def __init__(self, kv: Optional[KVStore] = None):
        self.kv = kv if kv is not None else MemoryKV()

    # -- node plane --------------------------------------------------------

    def node(self, node_hash: bytes) -> Optional[bytes]:
        return self.kv.get(_NODE + bytes(node_hash))

    def _refs(self, node_hash: bytes) -> int:
        raw = self.kv.get(_REF + bytes(node_hash))
        return 0 if not raw else int.from_bytes(raw, "big")

    def _set_refs(self, node_hash: bytes, count: int) -> None:
        if count <= 0:
            self.kv.delete(_REF + bytes(node_hash))
        else:
            self.kv.put(_REF + bytes(node_hash),
                        count.to_bytes(4, "big"))

    # -- commit ------------------------------------------------------------

    def commit(self, trie: Trie) -> bytes:
        """Persist the trie's hash-referenced nodes and take an external
        reference on the root. Returns the root hash (EMPTY_ROOT commits
        nothing).

        Reference model = the reference's edge counts
        (trie/database.go): a node's count is (number of stored parent
        nodes holding its hash) + (external root references). A node
        already present is a shared subtree — its edges are already
        counted, so the walk prunes there; nothing double-counts."""
        root = trie.root_hash()
        if root == EMPTY_ROOT:
            return root
        self._store(trie._root, is_root=True)
        self._incref(root)
        return root

    def _store(self, node, is_root: bool = False) -> None:
        raw = rlp_encode(_CODEC._node_structure(node))
        if not is_root and len(raw) < 32:
            # embedded in the parent's blob; embedded nodes cannot hold
            # hash references (a 32-byte ref alone makes a node >= 32)
            return
        key = keccak256(raw)
        if self.kv.get(_NODE + key) is not None:
            return  # shared subtree: present, edges already counted
        self.kv.put(_NODE + key, raw)
        for child in _child_hashes(rlp_decode(raw)):
            self._incref(child)
        if isinstance(node, _Extension):
            self._store(node.child)
        elif isinstance(node, _Branch):
            for child in node.children:
                if child is not None:
                    self._store(child)

    def _incref(self, node_hash: bytes) -> None:
        self._set_refs(node_hash, self._refs(node_hash) + 1)

    def reference(self, root: bytes) -> None:
        """Take an additional external reference on a stored root."""
        if root != EMPTY_ROOT:
            self._incref(root)

    # -- GC ----------------------------------------------------------------

    def dereference(self, root: bytes) -> int:
        """Drop one external reference on `root`; nodes whose count
        reaches zero are deleted, cascading into children — nodes shared
        with still-referenced roots survive (their edge counts hold).
        Returns the number of nodes deleted."""
        if root == EMPTY_ROOT:
            return 0
        node_hash = bytes(root)
        count = self._refs(node_hash)
        if count == 0:
            return 0  # unknown or already collected
        self._set_refs(node_hash, count - 1)
        if count > 1:
            return 0
        return self._collect(node_hash)

    def _collect(self, node_hash: bytes) -> int:
        blob = self.node(node_hash)
        if blob is None:
            return 0
        self.kv.delete(_NODE + node_hash)
        self.kv.delete(_REF + node_hash)
        deleted = 1
        for child in _child_hashes(rlp_decode(blob)):
            remaining = self._refs(child) - 1
            self._set_refs(child, remaining)
            if remaining <= 0:
                deleted += self._collect(child)
        return deleted

    # -- load --------------------------------------------------------------

    def load(self, root: bytes) -> Trie:
        """Reconstruct a Trie from stored nodes (raises KeyError on a
        missing node — an incomplete sync)."""
        trie = Trie()
        if root == EMPTY_ROOT:
            return trie
        trie._root = self._load_node(bytes(root))
        return trie

    def _load_node(self, node_hash: bytes):
        blob = self.node(node_hash)
        if blob is None:
            raise KeyError(f"missing trie node {node_hash.hex()}")
        return self._from_structure(rlp_decode(blob))

    def _from_structure(self, structure):
        if not isinstance(structure, list):
            raise ValueError("malformed stored node")
        if len(structure) == 2:
            path, is_leaf = _hp_decode(structure[0])
            if is_leaf:
                return _Leaf(path, structure[1])
            return _Extension(path, self._resolve(structure[1]))
        if len(structure) == 17:
            branch = _Branch()
            for i, child in enumerate(structure[:16]):
                if isinstance(child, list):
                    branch.children[i] = self._from_structure(child)
                elif child != b"":
                    branch.children[i] = self._load_node(bytes(child))
            if structure[16] != b"":
                branch.value = structure[16]
            return branch
        raise ValueError("malformed stored node")

    def _resolve(self, child):
        if isinstance(child, list):
            return self._from_structure(child)
        return self._load_node(bytes(child))


class TrieSync:
    """Pull a trie by node hash from a remote source into a database
    (trie/sync.go analog): breadth-first over missing nodes, every blob
    verified against the hash that requested it before it is stored."""

    def __init__(self, db: TrieDatabase):
        self.db = db

    def missing(self, root: bytes, limit: int = 256) -> List[bytes]:
        """Frontier of node hashes reachable from `root` that the
        database does not hold yet."""
        if root == EMPTY_ROOT:
            return []
        out: List[bytes] = []
        seen = {bytes(root)}  # dedup: a node can have several parents,
        # and double-fetching it would double-incref its children
        queue = [bytes(root)]
        while queue and len(out) < limit:
            node_hash = queue.pop(0)
            blob = self.db.node(node_hash)
            if blob is None:
                out.append(node_hash)
                continue
            for child in _child_hashes(rlp_decode(blob)):
                if child not in seen:
                    seen.add(child)
                    queue.append(child)
        return out

    def run(self, root: bytes, fetch: Callable[[bytes], Optional[bytes]],
            max_nodes: int = 1_000_000) -> int:
        """Sync until the trie under `root` is complete; returns nodes
        fetched. Raises ValueError on a blob that fails hash
        verification, KeyError when the source cannot provide a node."""
        fetched = 0
        while fetched < max_nodes:
            frontier = self.missing(root)
            if not frontier:
                break
            for node_hash in frontier:
                blob = fetch(node_hash)
                if blob is None:
                    raise KeyError(f"source missing node {node_hash.hex()}")
                if keccak256(blob) != node_hash:
                    raise ValueError(
                        f"node {node_hash.hex()} failed verification")
                self.db.kv.put(_NODE + node_hash, blob)
                # keep the edge counts consistent with commit(): each
                # stored parent references its hash children once
                for child in _child_hashes(rlp_decode(blob)):
                    self.db._incref(child)
                fetched += 1
        if root != EMPTY_ROOT and self.db._refs(bytes(root)) == 0:
            self.db._incref(bytes(root))  # the external root reference
        return fetched
