"""Per-shard persistence façade.

Parity: `sharding/shard.go` — header/body CRUD keyed by hash/chunk-root,
availability bits, and the canonical (shardID, period) -> header index, with
byte-identical lookup-key derivation (`shard.go:237-249`:
`BytesToHash("availability-lookup:<0xroot>")` and
`BytesToHash("canonical-collation-lookup:shardID=<d>,period=<d>")`, keeping
the LAST 32 bytes of the formatted string).
"""

from __future__ import annotations

from typing import Optional

from gethsharding_tpu.core.derive_sha import chunk_root as compute_chunk_root
from gethsharding_tpu.core.types import (
    Collation,
    CollationHeader,
    deserialize_blob_to_txs,
)
from gethsharding_tpu.db.kv import KVStore
from gethsharding_tpu.utils.hexbytes import Hash32


class ShardError(Exception):
    pass


def data_availability_lookup_key(chunk_root: Hash32) -> Hash32:
    return Hash32(f"availability-lookup:0x{bytes(chunk_root).hex()}".encode())


def canonical_collation_lookup_key(shard_id: int, period: int) -> Hash32:
    return Hash32(
        f"canonical-collation-lookup:shardID={shard_id},period={period}".encode()
    )


class Shard:
    """Fetch/store collations for one shard over any KVStore engine."""

    def __init__(self, shard_id: int, shard_db: KVStore):
        self.shard_id = shard_id
        self._db = shard_db

    def validate_shard_id(self, header: CollationHeader) -> None:
        if header.shard_id != self.shard_id:
            raise ShardError(
                f"collation does not belong to shard {self.shard_id} but "
                f"instead has shardID {header.shard_id}"
            )

    # -- reads -------------------------------------------------------------

    def header_by_hash(self, header_hash: Hash32) -> CollationHeader:
        encoded = self._db.get(bytes(header_hash))
        if not encoded:
            raise ShardError(f"no value set for header hash: {header_hash.hex_str}")
        return CollationHeader.decode_rlp(encoded)

    def collation_by_header_hash(self, header_hash: Hash32) -> Collation:
        header = self.header_by_hash(header_hash)
        body = self.body_by_chunk_root(header.chunk_root)
        txs = deserialize_blob_to_txs(body)
        return Collation(header=header, body=body, transactions=txs)

    def chunk_root_from_header_hash(self, header_hash: Hash32) -> Optional[Hash32]:
        return self.collation_by_header_hash(header_hash).header.chunk_root

    def canonical_header_hash(self, shard_id: int, period: int) -> Hash32:
        key = canonical_collation_lookup_key(shard_id, period)
        encoded = self._db.get(bytes(key))
        if not encoded:
            raise ShardError(
                f"no canonical collation header set for period={period}, "
                f"shardID={shard_id} pair"
            )
        return CollationHeader.decode_rlp(encoded).hash()

    def canonical_collation(self, shard_id: int, period: int) -> Collation:
        return self.collation_by_header_hash(
            self.canonical_header_hash(shard_id, period)
        )

    def body_by_chunk_root(self, chunk_root: Optional[Hash32]) -> bytes:
        if chunk_root is None:
            raise ShardError("header has no chunk root")
        body = self._db.get(bytes(chunk_root))
        if not body:
            raise ShardError(
                f"no corresponding body with chunk root found: {chunk_root.hex_str}"
            )
        return body

    def check_availability(self, header: CollationHeader) -> bool:
        if header.chunk_root is None:
            raise ShardError("header has no chunk root")
        key = data_availability_lookup_key(header.chunk_root)
        availability = self._db.get(bytes(key))
        if not availability:
            raise ShardError("availability not set for header")
        return availability[0] != 0

    # -- writes ------------------------------------------------------------

    def set_availability(self, chunk_root: Hash32, availability: bool) -> None:
        key = data_availability_lookup_key(chunk_root)
        self._db.put(bytes(key), b"\x01" if availability else b"\x00")

    def save_header(self, header: CollationHeader) -> None:
        if header.chunk_root is None:
            raise ShardError("header needs to have a chunk root set before saving")
        self._db.put(bytes(header.hash()), header.encode_rlp())

    def save_body(self, body: bytes) -> None:
        if not body:
            raise ShardError("body is empty")
        root = Hash32(compute_chunk_root(body))
        self.set_availability(root, True)
        self._db.put(bytes(root), body)

    def save_collation(self, collation: Collation) -> None:
        self.validate_shard_id(collation.header)
        self.save_header(collation.header)
        self.save_body(collation.body)

    def set_canonical(self, header: CollationHeader) -> None:
        self.validate_shard_id(header)
        # header and body must already be in the DB
        db_header = self.header_by_hash(header.hash())
        self.body_by_chunk_root(db_header.chunk_root)
        key = canonical_collation_lookup_key(db_header.shard_id, db_header.period)
        self._db.put(bytes(key), db_header.encode_rlp())
