"""Core consensus types and structures.

Parity targets (SURVEY.md §2.1, §2.4): `sharding/collation.go`,
`sharding/shard.go`, `core/types/` (Transaction, DeriveSha), `trie/`.
"""

from gethsharding_tpu.core.trie import Trie, EMPTY_ROOT  # noqa: F401
from gethsharding_tpu.core.trie_db import TrieDatabase, TrieSync  # noqa: F401
from gethsharding_tpu.core.derive_sha import derive_sha, chunk_root  # noqa: F401
from gethsharding_tpu.core.types import (  # noqa: F401
    CollationHeader,
    Collation,
    Transaction,
    serialize_txs_to_blob,
    deserialize_blob_to_txs,
    COLLATION_SIZE_LIMIT,
)
from gethsharding_tpu.core.shard import Shard  # noqa: F401
