"""Metrics: counters / gauges / timers behind a registry.

Parity: the `metrics/` go-metrics fork (registry `metrics.go:22-39`,
process collectors :42, expvar/influx exporters) scoped to what the
sharding framework actually needs natively (SURVEY.md §7.8): the two
BASELINE metrics — aggregate signature verifications/sec and collation
validate latency percentiles — plus per-actor operation counters.

Like the reference's `metrics.Enabled` gate, collection is cheap enough
to leave on; the `--metrics` CLI flag controls *reporting*. Timers keep a
bounded sample reservoir for percentile snapshots (the go-metrics
ExpDecaySample analog, simplified to a ring buffer — recent-window
percentiles, which is what a validate-latency dashboard wants).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class Counter:
    """Monotonic event count with a creation-time rate."""

    def __init__(self) -> None:
        self._value = 0
        self._t0 = time.monotonic()
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def rate(self) -> float:
        """Events/sec since creation."""
        elapsed = time.monotonic() - self._t0
        return self._value / elapsed if elapsed > 0 else 0.0

    def snapshot(self) -> dict:
        return {"type": "counter", "count": self._value,
                "rate_per_s": round(self.rate(), 3)}


class Gauge:
    """Last-written value."""

    def __init__(self) -> None:
        self._value: float = 0.0

    def set(self, value: float) -> None:
        self._value = value

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._value}


class Histogram:
    """Fixed-bucket distribution of discrete observations.

    The right shape for batch sizes (the serving layer's coalescing
    evidence): `Timer`'s reservoir percentiles interpolate between
    sample values, which is meaningless for discrete quantities that
    only ever take bucket-shaped values — a histogram reports how many
    observations fell at-or-below each bound, exactly.

    Snapshot fields are FLAT (``le_<bound>`` / ``le_inf`` counts next
    to ``count``/``mean``) so the influx exporter and the dashboard
    render them without nested-dict special cases.
    """

    DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)

    def __init__(self, buckets=DEFAULT_BUCKETS) -> None:
        if not buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._bounds = tuple(sorted(buckets))
        # one slot per bound + the overflow (> last bound) slot
        self._counts = [0] * (len(self._bounds) + 1)
        self._count = 0
        self._total = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        slot = len(self._bounds)
        for i, bound in enumerate(self._bounds):
            if value <= bound:
                slot = i
                break
        with self._lock:
            self._counts[slot] += 1
            self._count += 1
            self._total += value

    @property
    def count(self) -> int:
        return self._count

    def mean(self) -> float:
        return self._total / self._count if self._count else 0.0

    def bucket_counts(self) -> Dict[str, int]:
        """Per-bucket counts, NON-cumulative (each observation lands in
        exactly one slot)."""
        with self._lock:
            counts = list(self._counts)
        out = {f"le_{bound:g}": counts[i]
               for i, bound in enumerate(self._bounds)}
        out["le_inf"] = counts[-1]
        return out

    def snapshot(self) -> dict:
        return {"type": "histogram", "count": self._count,
                "mean": round(self.mean(), 3), **self.bucket_counts()}


class Timer:
    """Duration observations with percentile snapshots over a recent
    window (ring buffer of the last `reservoir` observations)."""

    def __init__(self, reservoir: int = 1024) -> None:
        self._samples: List[float] = []
        self._reservoir = reservoir
        self._count = 0
        self._total = 0.0
        self._next = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            if len(self._samples) < self._reservoir:
                self._samples.append(seconds)
            else:  # ring overwrite: recent-window percentiles
                self._samples[self._next] = seconds
                self._next = (self._next + 1) % self._reservoir

    def time(self) -> "_TimerContext":
        return _TimerContext(self)

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, q: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            ordered = sorted(self._samples)
        idx = min(int(q * len(ordered)), len(ordered) - 1)
        return ordered[idx]

    def mean(self) -> float:
        return self._total / self._count if self._count else 0.0

    def snapshot(self) -> dict:
        return {
            "type": "timer", "count": self._count,
            "mean_s": round(self.mean(), 6),
            "p50_s": round(self.percentile(0.50), 6),
            "p95_s": round(self.percentile(0.95), 6),
            "p99_s": round(self.percentile(0.99), 6),
        }


class _TimerContext:
    def __init__(self, timer: Timer) -> None:
        self._timer = timer

    def __enter__(self) -> "_TimerContext":
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._timer.observe(time.monotonic() - self._start)


class Registry:
    """Named metric registry (metrics.Registry parity)."""

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_register(self, name: str, factory):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = factory()
                self._metrics[name] = metric
            return metric

    def counter(self, name: str) -> Counter:
        return self._get_or_register(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_register(name, Gauge)

    def timer(self, name: str) -> Timer:
        return self._get_or_register(name, Timer)

    def histogram(self, name: str, buckets=None) -> Histogram:
        """`buckets` applies only on first registration (like every
        metric here, the first caller defines the instrument)."""
        factory = (Histogram if buckets is None
                   else (lambda: Histogram(buckets)))
        return self._get_or_register(name, factory)

    def get(self, name: str) -> Optional[object]:
        return self._metrics.get(name)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._metrics.items())
        return {name: metric.snapshot() for name, metric in sorted(items)}


# the default registry (metrics.DefaultRegistry parity)
DEFAULT_REGISTRY = Registry()


def counter(name: str) -> Counter:
    return DEFAULT_REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return DEFAULT_REGISTRY.gauge(name)


def timer(name: str) -> Timer:
    return DEFAULT_REGISTRY.timer(name)


def histogram(name: str, buckets=None) -> Histogram:
    return DEFAULT_REGISTRY.histogram(name, buckets=buckets)


class PeriodicReporter:
    """Logs a registry snapshot every `interval` seconds (the
    `CollectProcessMetrics` + exp exporter analog, to the log stream)."""

    def __init__(self, registry: Registry = DEFAULT_REGISTRY,
                 interval: float = 10.0, logger=None) -> None:
        import logging

        self.registry = registry
        self.interval = interval
        self.log = logger or logging.getLogger("metrics")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="metrics-reporter")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            for name, snap in self.registry.snapshot().items():
                self.log.info("%s %s", name, snap)


class InfluxLineExporter:
    """Registry snapshots as InfluxDB line protocol (the
    `metrics/influxdb` exporter analog), pushed on an interval to a
    file (Telegraf `tail`) or a UDP endpoint (InfluxDB's classic
    zero-dependency ingestion listener).

    One line per metric: ``<namespace>.<name> f1=v1,f2=v2 <ns-epoch>``
    with metric path separators normalized and every field emitted as a
    float (a stable schema: influx rejects type flips per field)."""

    def __init__(self, registry: Registry = DEFAULT_REGISTRY,
                 interval: float = 10.0, path: Optional[str] = None,
                 udp: Optional[tuple] = None,
                 namespace: str = "gethsharding") -> None:
        if (path is None) == (udp is None):
            raise ValueError("exactly one sink: path= or udp=(host, port)")
        self.registry = registry
        self.interval = interval
        self.path = path
        self.udp = udp
        self.namespace = namespace
        self.pushes = 0
        self._sock = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _escape(name: str) -> str:
        return (name.replace("/", ".").replace(" ", "_")
                .replace(",", "_").replace("=", "_"))

    def encode_snapshot(self, timestamp_ns: Optional[int] = None) -> bytes:
        ts = (time.time_ns() if timestamp_ns is None else timestamp_ns)
        lines = []
        for name, snap in self.registry.snapshot().items():
            fields = ",".join(
                f"{self._escape(k)}={float(v)}"
                for k, v in sorted(snap.items())
                if isinstance(v, (int, float)))
            if fields:
                lines.append(
                    f"{self.namespace}.{self._escape(name)} {fields} {ts}")
        return ("\n".join(lines) + "\n").encode() if lines else b""

    def push(self) -> None:
        payload = self.encode_snapshot()
        if not payload:
            return
        if self.path is not None:
            with open(self.path, "ab") as fh:
                fh.write(payload)
        else:
            import socket

            if self._sock is None:
                self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._sock.sendto(payload, self.udp)
        self.pushes += 1

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="metrics-influx")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        try:
            self.push()  # final flush
        except OSError:
            pass
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.push()
            except OSError:
                pass  # sink unavailable: keep collecting, retry next tick
