"""Metrics: counters / gauges / timers behind a registry.

Parity: the `metrics/` go-metrics fork (registry `metrics.go:22-39`,
process collectors :42, expvar/influx exporters) scoped to what the
sharding framework actually needs natively (SURVEY.md §7.8): the two
BASELINE metrics — aggregate signature verifications/sec and collation
validate latency percentiles — plus per-actor operation counters.

Like the reference's `metrics.Enabled` gate, collection is cheap enough
to leave on; the `--metrics` CLI flag controls *reporting*. Timers keep a
bounded sample reservoir for percentile snapshots (the go-metrics
ExpDecaySample analog, simplified to a ring buffer — recent-window
percentiles, which is what a validate-latency dashboard wants).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional


class Counter:
    """Monotonic event count with a creation-time rate AND a windowed
    one (the go-metrics `Meter` EWMA analog).

    `rate()` (events/sec since creation) goes stale on a long-running
    node — an hour of silence barely moves it. `rate_1m()` is the
    1-minute exponentially-weighted moving average over 5-second ticks
    (go-metrics `meter.go` constants), advanced lazily on read so idle
    counters cost nothing between snapshots."""

    _TICK_S = 5.0
    _ALPHA_1M = 1.0 - math.exp(-_TICK_S / 60.0)

    def __init__(self) -> None:
        self._value = 0
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        # EWMA state: events since the last tick, the tick clock, and
        # the smoothed per-second rate (unset until the first tick)
        self._uncounted = 0
        self._last_tick = self._t0
        self._ewma: Optional[float] = None

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n
            self._uncounted += n

    @property
    def value(self) -> int:
        return self._value

    def rate(self) -> float:
        """Events/sec since creation."""
        elapsed = time.monotonic() - self._t0
        return self._value / elapsed if elapsed > 0 else 0.0

    def rate_1m(self, now: Optional[float] = None) -> float:
        """Events/sec, 1-minute EWMA (0.0 until the first 5 s tick)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            ticks = int((now - self._last_tick) / self._TICK_S)
            if ticks > 0:
                # lazy ticking must agree with a real periodic ticker:
                # spread the accumulated events evenly over the elapsed
                # ticks (crediting them all to one tick and then pure-
                # decaying would under-report steady rates on infrequent
                # reads). Constant per-tick rate makes the K-tick EWMA
                # update exact in closed form.
                instant = self._uncounted / (ticks * self._TICK_S)
                remaining = ticks
                if self._ewma is None:
                    self._ewma = instant  # go-metrics: first tick seeds
                    remaining -= 1
                self._ewma = instant + (self._ewma - instant) * (
                    (1.0 - self._ALPHA_1M) ** remaining)
                self._uncounted = 0
                self._last_tick += ticks * self._TICK_S
            return self._ewma or 0.0

    def snapshot(self) -> dict:
        return {"type": "counter", "count": self._value,
                "rate_per_s": round(self.rate(), 3),
                "rate_1m": round(self.rate_1m(), 3)}


class Gauge:
    """Last-written value."""

    def __init__(self) -> None:
        self._value: float = 0.0

    def set(self, value: float) -> None:
        self._value = value

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._value}


class Histogram:
    """Fixed-bucket distribution of discrete observations.

    The right shape for batch sizes (the serving layer's coalescing
    evidence): `Timer`'s reservoir percentiles interpolate between
    sample values, which is meaningless for discrete quantities that
    only ever take bucket-shaped values — a histogram reports how many
    observations fell at-or-below each bound, exactly.

    Snapshot fields are FLAT (``le_<bound>`` / ``le_inf`` counts next
    to ``count``/``mean``) so the influx exporter and the dashboard
    render them without nested-dict special cases.

    Bucket semantics are Prometheus's: ``le_*`` counts are CUMULATIVE
    (observations at-or-below the bound; ``le_inf`` == ``count``).
    The exact per-slot counts remain available under ``bucket_*`` keys
    (`slot_counts()`) — each observation lands in exactly one slot.
    """

    DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)

    def __init__(self, buckets=DEFAULT_BUCKETS) -> None:
        if not buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._bounds = tuple(sorted(buckets))
        # one slot per bound + the overflow (> last bound) slot
        self._counts = [0] * (len(self._bounds) + 1)
        self._count = 0
        self._total = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        slot = len(self._bounds)
        for i, bound in enumerate(self._bounds):
            if value <= bound:
                slot = i
                break
        with self._lock:
            self._counts[slot] += 1
            self._count += 1
            self._total += value

    @property
    def count(self) -> int:
        return self._count

    def mean(self) -> float:
        return self._total / self._count if self._count else 0.0

    @property
    def bounds(self) -> tuple:
        return self._bounds

    @property
    def total(self) -> float:
        return self._total

    def read(self) -> tuple:
        """ONE consistent locked read: (per-slot counts, count, total).
        Every derived view builds from this so a scrape racing
        observe() can never emit ``le_inf != count`` (the Prometheus
        histogram invariant)."""
        with self._lock:
            return list(self._counts), self._count, self._total

    def _cumulative(self, counts) -> Dict[str, int]:
        out: Dict[str, int] = {}
        running = 0
        for i, bound in enumerate(self._bounds):
            running += counts[i]
            out[f"le_{bound:g}"] = running
        out["le_inf"] = running + counts[-1]
        return out

    def _per_slot(self, counts) -> Dict[str, int]:
        out = {f"bucket_{bound:g}": counts[i]
               for i, bound in enumerate(self._bounds)}
        out["bucket_inf"] = counts[-1]
        return out

    def bucket_counts(self) -> Dict[str, int]:
        """CUMULATIVE at-or-below counts under Prometheus ``le_*`` keys
        (what the name has always implied; ``le_inf`` == ``count``)."""
        return self._cumulative(self.read()[0])

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile from the cumulative buckets, linear
        interpolation WITHIN the bucket the target rank falls in (the
        Prometheus `histogram_quantile` estimator): the first bucket
        interpolates from 0, the overflow bucket clamps to the largest
        finite bound — an estimator cannot invent an upper edge for
        +Inf. 0.0 with no observations."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        counts, count, _ = self.read()
        if count == 0:
            return 0.0
        target = q * count
        running = 0
        lower = 0.0
        for i, bound in enumerate(self._bounds):
            if running + counts[i] >= target:
                if counts[i] == 0:
                    return float(bound)
                frac = (target - running) / counts[i]
                return lower + (bound - lower) * frac
            running += counts[i]
            lower = float(bound)
        return float(self._bounds[-1])

    def slot_counts(self) -> Dict[str, int]:
        """EXACT per-slot counts under ``bucket_*`` keys (each
        observation in exactly one slot; ``bucket_inf`` is overflow)."""
        return self._per_slot(self.read()[0])

    def snapshot(self) -> dict:
        counts, count, total = self.read()
        return {"type": "histogram", "count": count,
                "mean": round(total / count if count else 0.0, 3),
                # bucket-interpolated percentiles next to the raw
                # buckets: /status renders snapshots verbatim, so the
                # serving/fleet sections show p50/p95/p99 directly
                "p50": round(self.quantile(0.50), 4),
                "p95": round(self.quantile(0.95), 4),
                "p99": round(self.quantile(0.99), 4),
                **self._cumulative(counts), **self._per_slot(counts)}


class Timer:
    """Duration observations with percentile snapshots over a recent
    window (ring buffer of the last `reservoir` observations)."""

    def __init__(self, reservoir: int = 1024) -> None:
        self._samples: List[float] = []
        self._reservoir = reservoir
        self._count = 0
        self._total = 0.0
        self._next = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            if len(self._samples) < self._reservoir:
                self._samples.append(seconds)
            else:  # ring overwrite: recent-window percentiles
                self._samples[self._next] = seconds
                self._next = (self._next + 1) % self._reservoir

    def time(self) -> "_TimerContext":
        return _TimerContext(self)

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, q: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            ordered = sorted(self._samples)
        idx = min(int(q * len(ordered)), len(ordered) - 1)
        return ordered[idx]

    def mean(self) -> float:
        return self._total / self._count if self._count else 0.0

    def snapshot(self) -> dict:
        return {
            "type": "timer", "count": self._count,
            "mean_s": round(self.mean(), 6),
            "p50_s": round(self.percentile(0.50), 6),
            "p95_s": round(self.percentile(0.95), 6),
            "p99_s": round(self.percentile(0.99), 6),
        }


class _TimerContext:
    def __init__(self, timer: Timer) -> None:
        self._timer = timer

    def __enter__(self) -> "_TimerContext":
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._timer.observe(time.monotonic() - self._start)


class Registry:
    """Named metric registry (metrics.Registry parity)."""

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_register(self, name: str, factory):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = factory()
                self._metrics[name] = metric
            return metric

    def counter(self, name: str) -> Counter:
        return self._get_or_register(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_register(name, Gauge)

    def timer(self, name: str) -> Timer:
        return self._get_or_register(name, Timer)

    def histogram(self, name: str, buckets=None) -> Histogram:
        """`buckets` applies only on first registration (like every
        metric here, the first caller defines the instrument)."""
        factory = (Histogram if buckets is None
                   else (lambda: Histogram(buckets)))
        return self._get_or_register(name, factory)

    def get(self, name: str) -> Optional[object]:
        return self._metrics.get(name)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._metrics.items())
        return {name: metric.snapshot() for name, metric in sorted(items)}


# the default registry (metrics.DefaultRegistry parity)
DEFAULT_REGISTRY = Registry()


def counter(name: str) -> Counter:
    return DEFAULT_REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return DEFAULT_REGISTRY.gauge(name)


def timer(name: str) -> Timer:
    return DEFAULT_REGISTRY.timer(name)


def histogram(name: str, buckets=None) -> Histogram:
    return DEFAULT_REGISTRY.histogram(name, buckets=buckets)


class PeriodicReporter:
    """Logs a registry snapshot every `interval` seconds (the
    `CollectProcessMetrics` + exp exporter analog, to the log stream)."""

    def __init__(self, registry: Registry = DEFAULT_REGISTRY,
                 interval: float = 10.0, logger=None) -> None:
        import logging

        self.registry = registry
        self.interval = interval
        self.log = logger or logging.getLogger("metrics")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="metrics-reporter")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            for name, snap in self.registry.snapshot().items():
                self.log.info("%s %s", name, snap)


class InfluxLineExporter:
    """Registry snapshots as InfluxDB line protocol (the
    `metrics/influxdb` exporter analog), pushed on an interval to a
    file (Telegraf `tail`) or a UDP endpoint (InfluxDB's classic
    zero-dependency ingestion listener).

    One line per metric: ``<namespace>.<name> f1=v1,f2=v2 <ns-epoch>``
    with metric path separators normalized and every field emitted as a
    float (a stable schema: influx rejects type flips per field).
    Histogram lines carry BOTH the cumulative ``le_*`` fields and the
    exact per-slot ``bucket_*`` fields of the snapshot."""

    def __init__(self, registry: Registry = DEFAULT_REGISTRY,
                 interval: float = 10.0, path: Optional[str] = None,
                 udp: Optional[tuple] = None,
                 namespace: str = "gethsharding") -> None:
        if (path is None) == (udp is None):
            raise ValueError("exactly one sink: path= or udp=(host, port)")
        self.registry = registry
        self.interval = interval
        self.path = path
        self.udp = udp
        self.namespace = namespace
        self.pushes = 0
        self._sock = None
        # push() runs on the reporter thread AND on stop()'s final
        # flush (whose join is bounded and may time out with the
        # reporter mid-push): the socket lazy-init and the pushes
        # counter need a real guard, not a single-writer convention.
        # _closed (set under the same lock after the final flush)
        # stops a timed-out straggler reporter from lazily RE-creating
        # the socket stop() just closed and leaking it.
        self._closed = False
        self._push_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _escape(name: str) -> str:
        return (name.replace("/", ".").replace(" ", "_")
                .replace(",", "_").replace("=", "_"))

    def encode_snapshot(self, timestamp_ns: Optional[int] = None) -> bytes:
        ts = (time.time_ns() if timestamp_ns is None else timestamp_ns)
        lines = []
        for name, snap in self.registry.snapshot().items():
            fields = ",".join(
                f"{self._escape(k)}={float(v)}"
                for k, v in sorted(snap.items())
                if isinstance(v, (int, float)))
            if fields:
                lines.append(
                    f"{self.namespace}.{self._escape(name)} {fields} {ts}")
        return ("\n".join(lines) + "\n").encode() if lines else b""

    def push(self) -> None:
        payload = self.encode_snapshot()
        if not payload:
            return
        with self._push_lock:
            if self._closed:
                return  # stop() already final-flushed and closed
            if self.path is not None:
                with open(self.path, "ab") as fh:
                    fh.write(payload)
            else:
                import socket

                if self._sock is None:
                    self._sock = socket.socket(socket.AF_INET,
                                               socket.SOCK_DGRAM)
                self._sock.sendto(payload, self.udp)
            self.pushes += 1

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="metrics-influx")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        try:
            self.push()  # final flush
        except OSError:
            pass
        with self._push_lock:
            self._closed = True
            if self._sock is not None:
                self._sock.close()
                self._sock = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.push()
            except OSError:
                pass  # sink unavailable: keep collecting, retry next tick


# -- Prometheus text exposition (scrape without Telegraf) -------------------


def _prom_name(name: str, namespace: str) -> str:
    """Metric path -> a legal Prometheus metric name."""
    import re

    flat = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return f"{namespace}_{flat}" if namespace else flat


def prometheus_text(registry: Registry = DEFAULT_REGISTRY,
                    namespace: str = "gethsharding") -> str:
    """The registry as Prometheus text exposition format (0.0.4) — the
    ``GET /metrics?format=prom`` payload, so a node is scrapeable with
    no Telegraf/Influx hop:

    - Counter   -> ``<name>_total`` counter (+ ``<name>_rate_1m`` gauge)
    - Gauge     -> gauge
    - Timer     -> summary (quantiles 0.5/0.95/0.99, ``_count``/``_sum``)
    - Histogram -> histogram (cumulative ``_bucket{le=...}``,
      ``le="+Inf"`` == ``_count``, plus ``_sum``)
    """
    with registry._lock:
        items = sorted(registry._metrics.items())
    lines: List[str] = []
    for name, metric in items:
        prom = _prom_name(name, namespace)
        if isinstance(metric, Counter):
            lines += [f"# TYPE {prom}_total counter",
                      f"{prom}_total {metric.value}",
                      f"# TYPE {prom}_rate_1m gauge",
                      f"{prom}_rate_1m {metric.rate_1m():g}"]
        elif isinstance(metric, Gauge):
            lines += [f"# TYPE {prom} gauge", f"{prom} {metric.value:g}"]
        elif isinstance(metric, Timer):
            lines.append(f"# TYPE {prom} summary")
            for q in (0.5, 0.95, 0.99):
                lines.append(
                    f'{prom}{{quantile="{q:g}"}} {metric.percentile(q):g}')
            lines += [f"{prom}_count {metric.count}",
                      f"{prom}_sum {metric.mean() * metric.count:g}"]
        elif isinstance(metric, Histogram):
            lines.append(f"# TYPE {prom} histogram")
            # ONE locked read: +Inf bucket, _count and _sum must agree
            # even when a scrape races observe()
            counts, count, total = metric.read()
            cumulative = metric._cumulative(counts)
            for bound in metric.bounds:
                lines.append(f'{prom}_bucket{{le="{bound:g}"}} '
                             f'{cumulative[f"le_{bound:g}"]}')
            lines += [f'{prom}_bucket{{le="+Inf"}} {cumulative["le_inf"]}',
                      f"{prom}_count {count}",
                      f"{prom}_sum {total:g}"]
    # never empty: a scraper (or the observability smoke step) reading
    # zero bytes cannot tell "no metrics yet" from a broken endpoint
    return "\n".join(lines) + "\n" if lines else "# empty registry\n"
