"""Key-value store interface + in-memory and SQLite-backed engines.

Mirrors the `ethdb.Database` contract (`ethdb/interface.go`: Put/Get/Has/
Delete/Close + batch) and `sharding/database/inmemory.go` (ShardKV map).
SQLite (stdlib) stands in for LevelDB as the durable engine; it offers the
same ordered-KV semantics the shard layer needs and requires no external
dependency.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, Iterator, Optional, Tuple


class KVStore:
    """Abstract Get/Put/Has/Delete byte-keyed store."""

    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def items(self) -> Iterator[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def keys(self, prefix: bytes = b"") -> Iterator[bytes]:
        """Keys under `prefix`, WITHOUT materializing values — the
        cheap scan for small namespaces (e.g. the vote journal) living
        inside a store whose values can be large (chunk blobs).
        Engines override with an index-only query where they can."""
        prefix = bytes(prefix)
        return iter([key for key, _ in self.items()
                     if key.startswith(prefix)])


class MemoryKV(KVStore):
    """Thread-safe in-memory map (parity: ShardKV, ethdb.MemDatabase)."""

    def __init__(self):
        self._data: Dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._data.get(bytes(key))

    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._data[bytes(key)] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._data.pop(bytes(key), None)

    def items(self):
        with self._lock:
            return iter(list(self._data.items()))

    def keys(self, prefix: bytes = b""):
        prefix = bytes(prefix)
        with self._lock:
            return iter([key for key in self._data
                         if key.startswith(prefix)])

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)


class SqliteKV(KVStore):
    """Durable KV store over stdlib SQLite (LevelDB stand-in)."""

    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            # WAL + NORMAL matches the durability class of the LevelDB
            # this stands in for (ethdb writes are not fsync-per-put
            # either); without it every put pays a full journal fsync —
            # an order of magnitude on spinning/virtual disks
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
            )
            self._conn.commit()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (bytes(key),)
            ).fetchone()
        return None if row is None else bytes(row[0])

    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) "
                "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (bytes(key), bytes(value)),
            )
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (bytes(key),))
            self._conn.commit()

    def items(self):
        with self._lock:
            rows = self._conn.execute("SELECT k, v FROM kv ORDER BY k").fetchall()
        return iter([(bytes(k), bytes(v)) for k, v in rows])

    def keys(self, prefix: bytes = b""):
        # index-only range scan on the primary key: no value pages are
        # touched, so scanning a small namespace stays cheap even when
        # the store also holds large blobs
        prefix = bytes(prefix)
        with self._lock:
            if not prefix:
                rows = self._conn.execute(
                    "SELECT k FROM kv ORDER BY k").fetchall()
            else:
                # upper bound = prefix with its last byte incremented
                # (carrying over 0xff bytes); a prefix of all 0xff has
                # no upper bound
                upper = bytearray(prefix)
                while upper and upper[-1] == 0xFF:
                    upper.pop()
                if upper:
                    upper[-1] += 1
                    rows = self._conn.execute(
                        "SELECT k FROM kv WHERE k >= ? AND k < ? "
                        "ORDER BY k", (prefix, bytes(upper))).fetchall()
                else:
                    rows = self._conn.execute(
                        "SELECT k FROM kv WHERE k >= ? ORDER BY k",
                        (prefix,)).fetchall()
        return iter([bytes(k) for (k,) in rows])

    def close(self) -> None:
        with self._lock:
            self._conn.close()
