"""Shard storage engines.

Parity targets: `ethdb/` (LevelDB wrapper + MemDatabase) and
`sharding/database/` (ShardDB service, in-memory ShardKV). LevelDB itself is
not available here; the persistent engine is an embedded SQLite key-value
store with the same Get/Put/Has/Delete surface (`ethdb/interface.go`).
"""

from gethsharding_tpu.db.kv import KVStore, MemoryKV, SqliteKV  # noqa: F401
from gethsharding_tpu.db.shard_db import ShardDB  # noqa: F401
