"""ShardDB service: the node-attached storage engine.

Parity: `sharding/database/database.go` (NewShardDB :24, Start :47, Stop,
DB()). In-memory engine for tests/simulation; SQLite-backed engine (LevelDB
stand-in) for persistence under `<datadir>/<name>`.
"""

from __future__ import annotations

import os
from typing import Optional

from gethsharding_tpu.db.kv import KVStore, MemoryKV, SqliteKV


class ShardDB:
    """Storage service with the framework's Service lifecycle (start/stop)."""

    def __init__(self, data_dir: str = "", name: str = "shardchaindata",
                 in_memory: bool = True):
        self.data_dir = data_dir
        self.name = name
        self.in_memory = in_memory
        self._db: Optional[KVStore] = MemoryKV() if in_memory else None

    # -- Service lifecycle -------------------------------------------------

    def start(self) -> None:
        if not self.in_memory and self._db is None:
            os.makedirs(self.data_dir, exist_ok=True)
            self._db = SqliteKV(os.path.join(self.data_dir, self.name))

    def stop(self) -> None:
        if self._db is not None:
            self._db.close()
            if not self.in_memory:
                self._db = None  # restart reopens the file

    # -- accessors ---------------------------------------------------------

    @property
    def db(self) -> KVStore:
        if self._db is None:
            # open-on-first-access: construction-time wiring (ShardNode
            # hands the store to Shard before services start) must not
            # depend on lifecycle order
            self.start()
        return self._db
