"""Chain-process sync: a follower mainchain replicates a leader.

The reference topology runs "mainchain geth <-> devp2p <-> other geth
nodes" (SURVEY §1): block announcement/download between chain nodes
(`eth/handler.go:318` announce handling, `eth/downloader/downloader.go:
479` header+state sync). The r3 framework ran exactly ONE chain process
— this module closes that leg at dev-chain scale:

- HEADERS: the follower polls the leader's head, walks hashes back to
  the common ancestor (bounded by the snapshot horizon, exactly the
  reorg window `import_chain` supports), pulls the missing range over
  `shard_blockRange`, and imports it through `SimulatedMainchain.
  import_chain` — so every adopted block passes the consensus ENGINE's
  seal verification (clique signer rotation, dev-PoW nonce, fake) and
  reorgs follow longest-chain, just like a local import;
- STATE: dev-chain blocks are empty (SMC transactions execute outside
  block bodies), so the follower installs the leader's full-state
  checkpoint AT the imported head — the fast-sync pivot-state pull.
  `install_checkpoint` refuses any checkpoint whose (number, hash)
  doesn't match the engine-verified local head, and the pickle blob is
  only ever accepted from the CONFIGURED leader endpoint (never from
  gossip/untrusted peers).

A follower is a read replica: actors can point their read path at it
(load distribution, failover warm-standby); writes still go to the
leader, exactly like a light/full split.
"""

from __future__ import annotations

import logging
from typing import Optional

from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.rpc.client import RPCClient

log = logging.getLogger("chain.sync")


class ChainFollower(Service):
    """Keeps a local SimulatedMainchain in lockstep with a leader."""

    name = "chain-follower"
    supervisable = True

    def __init__(self, backend, leader_host: str, leader_port: int,
                 poll_interval: float = 0.2):
        super().__init__()
        self.backend = backend
        self.leader_host = leader_host
        self.leader_port = leader_port
        self.poll_interval = poll_interval
        self.blocks_imported = 0
        self.checkpoints_installed = 0
        self.reorgs_followed = 0
        self._rpc: Optional[RPCClient] = None
        self._installed_seq: Optional[list] = None

    def on_start(self) -> None:
        self._rpc = RPCClient(self.leader_host, self.leader_port)
        self.spawn(self._follow, name="chain-follower")

    def on_stop(self) -> None:
        if self._rpc is not None:
            self._rpc.close()

    # -- the sync loop -------------------------------------------------------

    def _follow(self) -> None:
        while not self.stopped():
            try:
                if self.sync_once():
                    self.record_success()
            except Exception as exc:
                self.record_failure(f"sync round failed: {exc}")
            if self.wait(self.poll_interval):
                return

    def sync_once(self) -> bool:
        """One sync round; True when local state advanced/refreshed."""
        # cheap steady-state gate: skip everything while the leader's
        # state seq matches what we installed (no RPC storm, no
        # per-round checkpoint deserialization)
        seq = self._rpc.call("shard_stateSeq")
        if seq == self._installed_seq:
            return False
        leader_head = self._rpc.call("shard_blockNumber")
        local_head = self.backend.block_number
        # find the common ancestor (hash walk, newest first; a reorg
        # deeper than the snapshot horizon cannot be followed — the same
        # bound import_chain/set_head enforce via state snapshots)
        probe = min(leader_head, local_head)
        ancestor = None
        while probe >= 0:
            theirs = self._rpc.call("shard_blockByNumber", probe)
            ours = self.backend.block_by_number(probe)
            if bytes(ours.hash) == codec.dec_bytes(theirs["hash"]):
                ancestor = probe
                break
            probe -= 1
            if local_head - probe >= self.backend.SNAPSHOT_HORIZON:
                self.record_error("leader diverged beyond the snapshot "
                                  "horizon; cannot follow the reorg")
                return False
        if ancestor is None:
            self.record_error("no common ancestor with the leader")
            return False

        if leader_head > ancestor:
            # chunked pull: the server caps one range at 4096 blocks, a
            # far-behind follower catches up over several calls
            blocks = []
            start = ancestor + 1
            while start <= leader_head:
                end = min(start + 4095, leader_head)
                blocks.extend(codec.dec_block(b) for b in self._rpc.call(
                    "shard_blockRange", start, end))
                start = end + 1
            if ancestor < local_head:
                self.reorgs_followed += 1
            adopted = self.backend.import_chain(blocks)
            if adopted == 0 and ancestor < local_head:
                # equal-length fork: import_chain's longest-wins keeps
                # the incumbent, but the LEADER is this follower's
                # source of truth — follow its branch explicitly
                self.backend.set_head(ancestor)
                adopted = self.backend.import_chain(blocks)
            self.blocks_imported += adopted
        elif ancestor < local_head:
            # leader is BEHIND on our branch (it reorged to a shorter
            # chain via set_head): follow it down
            self.backend.set_head(ancestor)
            self.reorgs_followed += 1

        checkpoint = self._rpc.call("shard_stateCheckpoint")
        if self.backend.install_checkpoint(checkpoint):
            self.checkpoints_installed += 1
            self._installed_seq = checkpoint.get("seq")
            return True
        return False  # leader advanced mid-round; next round catches up
