"""The Sharding Manager Contract as a native deterministic state machine.

In the reference, consensus lives in an EVM contract
(`sharding/contracts/sharding_manager.sol`) executed by geth and reached
over RPC + abigen bindings. Here the same state machine is a native,
deterministic transition system:

- `state_machine.SMC` — the authoritative host-side implementation with
  transaction-revert semantics matching the Solidity `require` rules
  bit-for-bit (vote bitfields, committee sampling, quirks included).
- `chain.SimulatedMainchain` — an in-process mainchain with
  pending/sealed blocks, deterministic block hashes, accounts, and manual
  `commit()` / `fast_forward()` — the SimulatedBackend-equivalent test
  fixture (`accounts/abi/bind/backends/simulated.go:53`).
- `vectorized` (see `gethsharding_tpu.ops`) — the fixed-shape array form
  of the vote/committee path that runs vmapped over shards on TPU.
"""

from gethsharding_tpu.smc.state_machine import (  # noqa: F401
    SMC,
    SMCRevert,
    Notary,
    CollationRecord,
)
from gethsharding_tpu.smc.chain import SimulatedMainchain, Block  # noqa: F401
