"""SimulatedMainchain: in-process mainchain with manual block production.

The framework's equivalent of `accounts/abi/bind/backends/simulated.go:53`
(SimulatedBackend) fused with the narrow mainchain surface the sharding
actors actually use (`sharding/mainchain/interfaces.go`): pending/sealed
blocks, deterministic block hashes, account balances, head subscriptions,
and the SMC deployed in-process instead of behind RPC+EVM.

Transactions execute against the *pending* block number (sealed height + 1)
and view calls against the latest sealed block, mirroring geth semantics.
`commit()` seals the pending block; `fast_forward(p)` mines p full periods
(the `MockClient.FastForward` pattern, `sharding/internal/client_helper.go:93`).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.params import Config, DEFAULT_CONFIG, ETHER
from gethsharding_tpu.smc.state_machine import SMC, SMCRevert
from gethsharding_tpu.utils.hexbytes import Address20, Hash32
from gethsharding_tpu.utils.rlp import rlp_encode, int_to_big_endian


@dataclass
class Block:
    number: int
    hash: Hash32
    parent_hash: Hash32
    # engine seal payload (consensus/consensus.go role): empty for the
    # fake engine, 8-byte nonce for dev PoW, vanity+65-byte signature
    # for clique — see smc/engine.py
    extra: bytes = b""


@dataclass
class Receipt:
    """Minimal tx receipt: status + events emitted during the call."""

    tx_hash: Hash32
    status: int
    block_number: int
    events: List = field(default_factory=list)


class SimulatedMainchain:
    """Deterministic dev chain hosting the SMC state machine."""

    def __init__(self, config: Config = DEFAULT_CONFIG,
                 genesis_balances: Optional[Dict[Address20, int]] = None,
                 engine=None):
        from gethsharding_tpu.smc.engine import FakeEngine

        self.config = config
        # consensus engine seam (consensus/consensus.go): decides the
        # seal payload + hash rule for produced blocks and the
        # verification rule for imported ones. The default FakeEngine
        # is byte-compatible with the pre-engine chain.
        self.engine = engine if engine is not None else FakeEngine()
        genesis = Block(number=0, hash=self._block_hash(0, Hash32()),
                        parent_hash=Hash32())
        self.blocks: List[Block] = [genesis]
        self.balances: Dict[Address20, int] = dict(genesis_balances or {})
        self.smc = SMC(config=config, blockhash_fn=self.blockhash)
        self._head_subscribers: List[Callable[[Block], None]] = []
        self._receipts: Dict[Hash32, Receipt] = {}
        self._tx_counter = 0
        self._lock = threading.RLock()
        # per-period vote log for the batched replay audit
        # (ops/smc_jax.submit_votes_batch vs the scalar machine): accepted
        # attempts + the sampling context snapshot + end-of-period state
        self._vote_audit: Dict[int, dict] = {}
        # chain rollback / reorg support (core/blockchain.go SetHead,
        # reorg): bounded ring of per-block state snapshots; heads beyond
        # the horizon cannot be rolled back to (the same limitation as a
        # non-archive geth node's pruned states). reorg_generation bumps
        # on every head rollback so downstream caches (the state mirror)
        # can tell a reorg from a racing stale refresh.
        self.SNAPSHOT_HORIZON = 32
        self._state_snaps: Dict[int, tuple] = {}
        self.reorg_generation = 0
        self._snapshot_state(0)

    # -- chain mechanics ---------------------------------------------------

    @staticmethod
    def _block_hash(number: int, parent_hash: Hash32) -> Hash32:
        return Hash32(keccak256(rlp_encode([int_to_big_endian(number),
                                            bytes(parent_hash)])))

    @property
    def block_number(self) -> int:
        """Latest sealed block number."""
        return self.blocks[-1].number

    @property
    def pending_block_number(self) -> int:
        return self.block_number + 1

    def current_period(self) -> int:
        return self.block_number // self.config.period_length

    def blockhash(self, number: int) -> Hash32:
        """Hash of a sealed block; zero for unknown/future (EVM blockhash)."""
        if 0 <= number < len(self.blocks):
            return self.blocks[number].hash
        return Hash32()

    def block_by_number(self, number: Optional[int] = None) -> Block:
        if number is None:
            return self.blocks[-1]
        return self.blocks[number]

    def commit(self) -> Block:
        """Seal the pending block and notify head subscribers."""
        with self._lock:
            parent = self.blocks[-1]
            block_hash, extra = self.engine.seal(parent.number + 1,
                                                 parent.hash)
            block = Block(
                number=parent.number + 1,
                hash=block_hash,
                parent_hash=parent.hash,
                extra=extra,
            )
            self.blocks.append(block)
            self.engine.finalize(block.number, block.parent_hash, extra)
            # a period ends when the pending block number crosses into the
            # next period: snapshot its end-of-period vote state for the
            # batched replay audit before any next-period tx can clear it
            old_pending = block.number
            plen = self.config.period_length
            if (old_pending + 1) // plen > old_pending // plen:
                self._finalize_vote_audit(old_pending // plen)
            self._snapshot_state(block.number)
            subscribers = list(self._head_subscribers)
        for callback in subscribers:
            callback(block)
        return block

    # -- rollback / reorg (core/blockchain.go SetHead + reorg) -------------

    def _snapshot_state(self, number: int) -> None:
        import copy

        fn = self.smc.blockhash_fn
        self.smc.blockhash_fn = None  # bound method: not copyable state
        # the audit log grows with chain age: snapshot only the rollback
        # window's worth (older periods' logs survive a rollback anyway —
        # a head inside the horizon can't reach them)
        period_floor = (number // self.config.period_length
                        - self.SNAPSHOT_HORIZON // self.config.period_length
                        - 1)
        audit = {p: v for p, v in self._vote_audit.items()
                 if p >= period_floor}
        try:
            snap = copy.deepcopy((self.smc, self.balances, audit,
                                  self.engine.snapshot()))
        finally:
            self.smc.blockhash_fn = fn
        self._state_snaps[number] = snap
        stale = number - self.SNAPSHOT_HORIZON
        if stale in self._state_snaps:
            del self._state_snaps[stale]

    def _rollback_locked(self, number: int) -> None:
        """Restore block `number`'s state + truncate (lock held)."""
        import copy

        if not 0 <= number <= self.block_number:
            raise ValueError(f"set_head({number}): head is "
                             f"{self.block_number}")
        snap = self._state_snaps.get(number)
        if snap is None:
            raise ValueError(
                f"state for block {number} pruned (horizon "
                f"{self.SNAPSHOT_HORIZON})")
        smc, balances, vote_audit, engine_state = copy.deepcopy(snap)
        smc.blockhash_fn = self.blockhash
        self.smc = smc
        self.balances = balances
        if engine_state is not None:
            self.engine.restore(engine_state)
        # audit logs for periods finalized BEFORE the target head are
        # identical on both branches — keep them (the snapshot only
        # carries the rollback window's worth); anything later comes
        # from the snapshot or is gone with the rolled-back blocks
        plen = self.config.period_length
        keep = {p: v for p, v in self._vote_audit.items()
                if (p + 1) * plen <= number}
        keep.update(vote_audit)
        self._vote_audit = keep
        del self.blocks[number + 1:]
        for n in list(self._state_snaps):
            if n > number:
                del self._state_snaps[n]
        self.reorg_generation += 1

    def set_head(self, number: int) -> Block:
        """Roll the chain back to `number` (SetHead parity): truncate the
        header chain, restore that block's state snapshot, notify head
        subscribers with the new head. Raises for future heads and for
        heads whose state has been pruned past the snapshot horizon."""
        with self._lock:
            self._rollback_locked(number)
            head = self.blocks[-1]
            subscribers = list(self._head_subscribers)
        for callback in subscribers:
            callback(head)
        return head

    def import_chain(self, blocks: Sequence[Block]) -> int:
        """Import a competing branch (core/blockchain.go:1002 InsertChain
        + reorg, scoped to the dev chain's empty blocks): the branch must
        link to a known block; it wins only if strictly longer than the
        current chain (the dev analog of higher total difficulty — ties
        keep the incumbent). Validation, rollback and adoption happen
        under ONE lock hold, so a concurrent commit() can neither
        interleave a block into the adopted branch nor invalidate the
        longest-wins decision. Returns the number of blocks adopted."""
        if not blocks:
            return 0
        import copy

        with self._lock:
            first = blocks[0]
            attach = first.number - 1
            if (not 0 <= attach <= self.block_number
                    or bytes(first.parent_hash)
                    != bytes(self.blocks[attach].hash)):
                raise ValueError("branch does not link to a known block")
            parent = self.blocks[attach]
            for block in blocks:  # internal linkage + numbering
                if (block.number != parent.number + 1
                        or bytes(block.parent_hash) != bytes(parent.hash)):
                    raise ValueError("broken branch linkage")
                parent = block
            if blocks[-1].number <= self.block_number:
                return 0  # not longer: incumbent stays canonical, and a
                # branch that cannot win needs no engine verification
                # (stale forks may attach beyond the snapshot horizon)
            # seal verification runs against the ATTACH POINT's engine
            # state, with finalize interleaved, so mid-branch
            # authorization changes rotate the expected signer exactly
            # as geth's per-block clique snapshots do
            # (clique.go snapshot()). The walked state is throwaway:
            # failure restores the incumbent's, adoption re-derives it
            # block by block below.
            attach_snap = self._state_snaps.get(attach)
            if attach_snap is None:
                raise ValueError(
                    f"state for block {attach} pruned (horizon "
                    f"{self.SNAPSHOT_HORIZON})")
            incumbent_engine = self.engine.snapshot()
            attach_engine = copy.deepcopy(attach_snap[3])
            if attach_engine is not None:
                self.engine.restore(attach_engine)
            try:
                for block in blocks:
                    self.engine.verify_header(block.number,
                                              block.parent_hash,
                                              block.extra, block.hash)
                    self.engine.finalize(block.number, block.parent_hash,
                                         block.extra)
            except BaseException:
                if incumbent_engine is not None:
                    self.engine.restore(incumbent_engine)
                raise
            self._rollback_locked(attach)  # also re-restores attach state
            self.blocks.extend(blocks)
            for block in blocks:
                self.engine.finalize(block.number, block.parent_hash,
                                     block.extra)
                self._snapshot_state(block.number)
            head = self.blocks[-1]
            subscribers = list(self._head_subscribers)
        for callback in subscribers:
            callback(head)
        return len(blocks)

    def state_seq(self) -> list:
        """Cheap monotonic state identity [reorg_gen, block, tx_count]:
        a follower skips the heavy checkpoint pull while it is
        unchanged (every SMC transaction bumps the tx counter)."""
        with self._lock:
            return [self.reorg_generation, self.block_number,
                    self._tx_counter]

    def state_checkpoint(self) -> dict:
        """Serialized full state at the CURRENT head — what a follower
        chain process installs after importing our headers (the
        fast-sync pivot-state pull, `eth/downloader/downloader.go:479`
        role at dev-chain scale). The blob is a pickle: followers must
        only install checkpoints from their CONFIGURED leader endpoint
        (smc/sync.py enforces that by construction), never from
        untrusted peers. The vote-audit log ships only the rollback
        window's worth (same pruning as _snapshot_state) so the blob
        does not grow with chain age."""
        import pickle

        with self._lock:
            fn = self.smc.blockhash_fn
            self.smc.blockhash_fn = None  # bound method: not picklable
            number = self.block_number
            period_floor = (number // self.config.period_length
                            - self.SNAPSHOT_HORIZON
                            // self.config.period_length - 1)
            audit = {p: v for p, v in self._vote_audit.items()
                     if p >= period_floor}
            try:
                blob = pickle.dumps((self.smc, self.balances, audit,
                                     self.engine.snapshot()))
            finally:
                self.smc.blockhash_fn = fn
            head = self.blocks[-1]
            return {"number": head.number,
                    "hash": bytes(head.hash).hex(),
                    "reorg_gen": self.reorg_generation,
                    "seq": [self.reorg_generation, number,
                            self._tx_counter],
                    "state": blob.hex()}

    def install_checkpoint(self, checkpoint: dict) -> bool:
        """Adopt a leader's state checkpoint. The checkpoint must match
        OUR current head (number + hash) — headers are imported and
        engine-verified first via `import_chain`; this only swaps in the
        state they commit to. Returns False when the head moved since
        the checkpoint was taken (caller retries next round)."""
        import pickle

        with self._lock:
            head = self.blocks[-1]
            if (checkpoint["number"] != head.number
                    or checkpoint["hash"] != bytes(head.hash).hex()):
                return False
            smc, balances, vote_audit, engine_state = pickle.loads(
                bytes.fromhex(checkpoint["state"]))
            smc.blockhash_fn = self.blockhash
            self.smc = smc
            self.balances = balances
            self._vote_audit = vote_audit
            if engine_state is not None:
                self.engine.restore(engine_state)
            # the head snapshot must reflect the synced state, or a later
            # rollback would resurrect the pre-sync one
            self._snapshot_state(head.number)
        return True

    def fast_forward(self, periods: int) -> None:
        """Mine `periods` full periods of blocks (client_helper.go:93)."""
        for _ in range(periods * self.config.period_length):
            self.commit()

    def subscribe_new_head(self, callback: Callable[[Block], None]) -> Callable[[], None]:
        """Register a head callback; returns an unsubscribe function."""
        self._head_subscribers.append(callback)

        def unsubscribe():
            if callback in self._head_subscribers:
                self._head_subscribers.remove(callback)

        return unsubscribe

    # -- accounts ----------------------------------------------------------

    def fund(self, account: Address20, amount: int = 10_000 * ETHER) -> None:
        # counted as a state mutation so followers' seq-gated checkpoint
        # pulls see dev-faucet changes too
        self._tx_counter += 1
        self.balances[account] = self.balances.get(account, 0) + amount

    def balance_of(self, account: Address20) -> int:
        return self.balances.get(account, 0)

    # -- SMC transaction surface ------------------------------------------
    # Each transact_* executes in the pending block, records a receipt, and
    # moves value. Reverts raise SMCRevert and leave no state change.

    def _new_tx_hash(self) -> Hash32:
        self._tx_counter += 1
        return Hash32(keccak256(b"tx" + self._tx_counter.to_bytes(8, "big")))

    def _record(self, events_before: int) -> Receipt:
        receipt = Receipt(
            tx_hash=self._new_tx_hash(),
            status=1,
            block_number=self.pending_block_number,
            events=self.smc.events[events_before:],
        )
        self._receipts[receipt.tx_hash] = receipt
        return receipt

    def transaction_receipt(self, tx_hash: Hash32) -> Optional[Receipt]:
        return self._receipts.get(tx_hash)

    def register_notary(self, sender: Address20, value: Optional[int] = None,
                        bls_pubkey=None, bls_pop=None) -> Receipt:
        with self._lock:
            deposit = self.config.notary_deposit if value is None else value
            if self.balances.get(sender, 0) < deposit:
                raise SMCRevert("insufficient balance for deposit")
            events_before = len(self.smc.events)
            self.smc.register_notary(sender, deposit, self.pending_block_number,
                                     bls_pubkey=bls_pubkey, bls_pop=bls_pop)
            self.balances[sender] -= deposit
            self._mark_pool_churn()
            return self._record(events_before)

    def deregister_notary(self, sender: Address20) -> Receipt:
        with self._lock:
            events_before = len(self.smc.events)
            self.smc.deregister_notary(sender, self.pending_block_number)
            self._mark_pool_churn()
            return self._record(events_before)

    def release_notary(self, sender: Address20) -> Receipt:
        with self._lock:
            events_before = len(self.smc.events)
            released = self.smc.release_notary(sender, self.pending_block_number)
            self.balances[sender] = self.balances.get(sender, 0) + released
            return self._record(events_before)

    def add_header(self, sender: Address20, shard_id: int, period: int,
                   chunk_root: Hash32, signature: bytes = b"") -> Receipt:
        with self._lock:
            events_before = len(self.smc.events)
            self.smc.add_header(sender, shard_id, period, chunk_root,
                                signature, self.pending_block_number)
            return self._record(events_before)

    def submit_vote(self, sender: Address20, shard_id: int, period: int,
                    index: int, chunk_root: Hash32, bls_sig=None) -> Receipt:
        with self._lock:
            events_before = len(self.smc.events)
            pre_last_approved = (
                dict(self.smc.last_approved_collation)
                if period not in self._vote_audit else None)
            self.smc.submit_vote(sender, shard_id, period, index, chunk_root,
                                 self.pending_block_number, bls_sig=bls_sig)
            self._log_vote(period, sender, shard_id, index, chunk_root,
                           pre_last_approved)
            return self._record(events_before)

    # -- SMC view surface (latest sealed block, like eth_call) ------------

    def get_notary_in_committee(self, sender: Address20, shard_id: int) -> Address20:
        return self.smc.get_notary_in_committee_view(
            sender, shard_id, self.block_number
        )

    def notary_registry(self, address: Address20):
        return self.smc.notary_registry.get(address)

    def collation_record(self, shard_id: int, period: int):
        return self.smc.collation_records.get((shard_id, period))

    def last_submitted_collation(self, shard_id: int) -> int:
        return self.smc.last_submitted_collation.get(shard_id, 0)

    def last_approved_collation(self, shard_id: int) -> int:
        return self.smc.last_approved_collation.get(shard_id, 0)

    def notary_by_pool_index(self, index: int) -> Optional[Address20]:
        """Pool slot -> notary address (None for empty/out-of-range slots)."""
        pool = self.smc.notary_pool
        return pool[index] if 0 <= index < len(pool) else None

    def committee_context(self) -> dict:
        """The sampling inputs for the CURRENT period in one view call:
        clients compute all-shard committee eligibility locally (one
        keccak batch) instead of one eth_call per shard — the reference's
        per-head x per-shard scan (`sharding/notary/notary.go:62`,
        SURVEY.md §3.1 hot loop) collapsed into a single round-trip.

        Mirrors `get_notary_in_committee_view`'s sample-size simulation
        exactly; `pool` is the raw slot array (None = emptied slot)."""
        with self._lock:
            smc = self.smc
            period = self.current_period()
            sample_size_last_updated = smc.sample_size_last_updated_period
            current_size = smc.current_period_notary_sample_size
            next_size = smc.next_period_notary_sample_size
            if period >= sample_size_last_updated:
                current_size = next_size
                sample_size_last_updated = period
            sample_size = (next_size if period > sample_size_last_updated
                           else current_size)
            latest_block = period * self.config.period_length - 1
            return {
                "period": period,
                "sample_size": sample_size,
                "blockhash": bytes(self.blockhash(latest_block)),
                "pool": [None if a is None else bytes(a)
                         for a in smc.notary_pool],
            }

    def has_voted(self, shard_id: int, index: int) -> bool:
        return self.smc.has_voted(shard_id, index)

    def get_vote_count(self, shard_id: int) -> int:
        return self.smc.get_vote_count(shard_id)

    def shard_count(self) -> int:
        return self.smc.shard_count

    # -- batched vote-replay audit ----------------------------------------
    # The chain logs every ACCEPTED submitVote together with a snapshot of
    # the sampling context (pool, sample size, period blockhash) taken at
    # the period's first vote, and the end-of-period vote state at the
    # period boundary. `verify_period_batch` replays the log through the
    # fixed-shape kernel `ops/smc_jax.submit_votes_batch` and checks the
    # result is byte-identical with what the scalar machine computed —
    # in-node failure detection for the batch path (SURVEY.md §5.3).

    def _mark_pool_churn(self) -> None:
        pending_period = self.pending_block_number // self.config.period_length
        entry = self._vote_audit.get(pending_period)
        if entry is not None:
            # pool mutated after the snapshot: sampling context no longer
            # reproducible for this period; skip its replay check
            entry["churned"] = True

    def _log_vote(self, period: int, sender: Address20, shard_id: int,
                  index: int, chunk_root: Hash32, pre_last_approved) -> None:
        entry = self._vote_audit.get(period)
        if entry is None:
            entry = {
                "attempts": [],
                "churned": False,
                # post-update value: SMC.submit_vote just ran
                # _update_notary_sample_size for this period
                "sample_size": self.smc.current_period_notary_sample_size,
                "pool": [bytes(a) if a is not None else None
                         for a in self.smc.notary_pool],
                "blockhash": bytes(self.blockhash(
                    period * self.config.period_length - 1)),
                "pre_last_approved": pre_last_approved or {},
                "final": None,
            }
            self._vote_audit[period] = entry
        reg = self.smc.notary_registry[sender]
        entry["attempts"].append({
            "shard": shard_id,
            "index": index,
            "pool_index": reg.pool_index,
            "sender": bytes(sender),
            "chunk_root": bytes(chunk_root),
        })

    def _finalize_vote_audit(self, period: int) -> None:
        entry = self._vote_audit.get(period)
        if entry is not None and entry["final"] is None:
            shards = {a["shard"] for a in entry["attempts"]}
            entry["final"] = {
                "words": {s: self.smc.current_vote.get(s, 0) for s in shards},
                "elected": {
                    s: bool(self.smc.collation_records[(s, period)].is_elected)
                    for s in shards
                    if (s, period) in self.smc.collation_records},
                "last_approved": {
                    s: self.smc.last_approved_collation.get(s, 0)
                    for s in shards},
            }
        # bound memory: keep a few recent periods only
        for p in [p for p in self._vote_audit if p < period - 8]:
            del self._vote_audit[p]

    def verify_period_batch(self, period: int) -> Optional[bool]:
        """Replay `period`'s accepted votes through the batch kernel and
        compare with the scalar outcome. True = byte-identical, False =
        divergence, None = not auditable (no votes, pool churn mid-period,
        or period not yet finalized)."""
        with self._lock:
            entry = self._vote_audit.get(period)
            if (entry is None or entry["churned"] or not entry["attempts"]
                    or entry["final"] is None):
                return None
            attempts = list(entry["attempts"])
            records = {
                s: self.smc.collation_records.get((s, period))
                for s in range(self.smc.shard_count)
            }
            snapshot = dict(entry)

        import numpy as np
        import jax.numpy as jnp

        from gethsharding_tpu.ops import smc_jax

        s_count = self.smc.shard_count
        committee = self.config.committee_size
        last_sub = np.zeros(s_count, np.int32)
        roots = np.zeros((s_count, 32), np.uint8)
        last_appr = np.zeros(s_count, np.int32)
        for s in range(s_count):
            last_appr[s] = snapshot["pre_last_approved"].get(s, 0)
            rec = records[s]
            if rec is not None:
                last_sub[s] = period
                roots[s] = np.frombuffer(bytes(rec.chunk_root), np.uint8)
        state = smc_jax.init_vote_state(s_count, committee)._replace(
            last_submitted=jnp.asarray(last_sub),
            chunk_root=jnp.asarray(roots),
            last_approved=jnp.asarray(last_appr),
        )
        pool = snapshot["pool"]
        pool_addr = np.zeros((max(len(pool), 1), 20), np.uint8)
        for i, addr in enumerate(pool):
            if addr is not None:
                pool_addr[i] = np.frombuffer(addr, np.uint8)
        n_att = len(attempts)
        att = smc_jax.VoteAttempts(
            shard=jnp.asarray([a["shard"] for a in attempts], jnp.int32),
            index=jnp.asarray([a["index"] for a in attempts], jnp.int32),
            pool_index=jnp.asarray([a["pool_index"] for a in attempts],
                                   jnp.int32),
            sender=jnp.asarray(np.stack(
                [np.frombuffer(a["sender"], np.uint8) for a in attempts])),
            chunk_root=jnp.asarray(np.stack(
                [np.frombuffer(a["chunk_root"], np.uint8) for a in attempts])),
            deposited=jnp.ones(n_att, bool),
            valid=jnp.ones(n_att, bool),
        )
        new_state, accepted = smc_jax.submit_votes_batch(
            state, jnp.asarray(pool_addr), att,
            period=jnp.int32(period),
            blockhash=jnp.asarray(
                np.frombuffer(snapshot["blockhash"], np.uint8)),
            sample_size=jnp.int32(snapshot["sample_size"]),
            committee_size=committee,
            quorum_size=self.config.quorum_size,
        )
        if not bool(np.asarray(accepted).all()):
            return False  # a scalar-accepted vote was rejected by the batch
        words = smc_jax.export_vote_word(
            np.asarray(new_state.has_voted), np.asarray(new_state.vote_count))
        final = snapshot["final"]
        elected = np.asarray(new_state.is_elected)
        approved = np.asarray(new_state.last_approved)
        for s in sorted({a["shard"] for a in attempts}):
            if words[s] != final["words"].get(s, 0):
                return False
            if bool(elected[s]) != final["elected"].get(s, False):
                return False
            if int(approved[s]) != final["last_approved"].get(s, 0):
                return False
        return True
