"""SimulatedMainchain: in-process mainchain with manual block production.

The framework's equivalent of `accounts/abi/bind/backends/simulated.go:53`
(SimulatedBackend) fused with the narrow mainchain surface the sharding
actors actually use (`sharding/mainchain/interfaces.go`): pending/sealed
blocks, deterministic block hashes, account balances, head subscriptions,
and the SMC deployed in-process instead of behind RPC+EVM.

Transactions execute against the *pending* block number (sealed height + 1)
and view calls against the latest sealed block, mirroring geth semantics.
`commit()` seals the pending block; `fast_forward(p)` mines p full periods
(the `MockClient.FastForward` pattern, `sharding/internal/client_helper.go:93`).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.params import Config, DEFAULT_CONFIG, ETHER
from gethsharding_tpu.smc.state_machine import SMC, SMCRevert
from gethsharding_tpu.utils.hexbytes import Address20, Hash32
from gethsharding_tpu.utils.rlp import rlp_encode, int_to_big_endian


@dataclass
class Block:
    number: int
    hash: Hash32
    parent_hash: Hash32


@dataclass
class Receipt:
    """Minimal tx receipt: status + events emitted during the call."""

    tx_hash: Hash32
    status: int
    block_number: int
    events: List = field(default_factory=list)


class SimulatedMainchain:
    """Deterministic dev chain hosting the SMC state machine."""

    def __init__(self, config: Config = DEFAULT_CONFIG,
                 genesis_balances: Optional[Dict[Address20, int]] = None):
        self.config = config
        genesis = Block(number=0, hash=self._block_hash(0, Hash32()),
                        parent_hash=Hash32())
        self.blocks: List[Block] = [genesis]
        self.balances: Dict[Address20, int] = dict(genesis_balances or {})
        self.smc = SMC(config=config, blockhash_fn=self.blockhash)
        self._head_subscribers: List[Callable[[Block], None]] = []
        self._receipts: Dict[Hash32, Receipt] = {}
        self._tx_counter = 0
        self._lock = threading.RLock()

    # -- chain mechanics ---------------------------------------------------

    @staticmethod
    def _block_hash(number: int, parent_hash: Hash32) -> Hash32:
        return Hash32(keccak256(rlp_encode([int_to_big_endian(number),
                                            bytes(parent_hash)])))

    @property
    def block_number(self) -> int:
        """Latest sealed block number."""
        return self.blocks[-1].number

    @property
    def pending_block_number(self) -> int:
        return self.block_number + 1

    def current_period(self) -> int:
        return self.block_number // self.config.period_length

    def blockhash(self, number: int) -> Hash32:
        """Hash of a sealed block; zero for unknown/future (EVM blockhash)."""
        if 0 <= number < len(self.blocks):
            return self.blocks[number].hash
        return Hash32()

    def block_by_number(self, number: Optional[int] = None) -> Block:
        if number is None:
            return self.blocks[-1]
        return self.blocks[number]

    def commit(self) -> Block:
        """Seal the pending block and notify head subscribers."""
        with self._lock:
            parent = self.blocks[-1]
            block = Block(
                number=parent.number + 1,
                hash=self._block_hash(parent.number + 1, parent.hash),
                parent_hash=parent.hash,
            )
            self.blocks.append(block)
            subscribers = list(self._head_subscribers)
        for callback in subscribers:
            callback(block)
        return block

    def fast_forward(self, periods: int) -> None:
        """Mine `periods` full periods of blocks (client_helper.go:93)."""
        for _ in range(periods * self.config.period_length):
            self.commit()

    def subscribe_new_head(self, callback: Callable[[Block], None]) -> Callable[[], None]:
        """Register a head callback; returns an unsubscribe function."""
        self._head_subscribers.append(callback)

        def unsubscribe():
            if callback in self._head_subscribers:
                self._head_subscribers.remove(callback)

        return unsubscribe

    # -- accounts ----------------------------------------------------------

    def fund(self, account: Address20, amount: int = 10_000 * ETHER) -> None:
        self.balances[account] = self.balances.get(account, 0) + amount

    def balance_of(self, account: Address20) -> int:
        return self.balances.get(account, 0)

    # -- SMC transaction surface ------------------------------------------
    # Each transact_* executes in the pending block, records a receipt, and
    # moves value. Reverts raise SMCRevert and leave no state change.

    def _new_tx_hash(self) -> Hash32:
        self._tx_counter += 1
        return Hash32(keccak256(b"tx" + self._tx_counter.to_bytes(8, "big")))

    def _record(self, events_before: int) -> Receipt:
        receipt = Receipt(
            tx_hash=self._new_tx_hash(),
            status=1,
            block_number=self.pending_block_number,
            events=self.smc.events[events_before:],
        )
        self._receipts[receipt.tx_hash] = receipt
        return receipt

    def transaction_receipt(self, tx_hash: Hash32) -> Optional[Receipt]:
        return self._receipts.get(tx_hash)

    def register_notary(self, sender: Address20, value: Optional[int] = None) -> Receipt:
        with self._lock:
            deposit = self.config.notary_deposit if value is None else value
            if self.balances.get(sender, 0) < deposit:
                raise SMCRevert("insufficient balance for deposit")
            events_before = len(self.smc.events)
            self.smc.register_notary(sender, deposit, self.pending_block_number)
            self.balances[sender] -= deposit
            return self._record(events_before)

    def deregister_notary(self, sender: Address20) -> Receipt:
        with self._lock:
            events_before = len(self.smc.events)
            self.smc.deregister_notary(sender, self.pending_block_number)
            return self._record(events_before)

    def release_notary(self, sender: Address20) -> Receipt:
        with self._lock:
            events_before = len(self.smc.events)
            released = self.smc.release_notary(sender, self.pending_block_number)
            self.balances[sender] = self.balances.get(sender, 0) + released
            return self._record(events_before)

    def add_header(self, sender: Address20, shard_id: int, period: int,
                   chunk_root: Hash32, signature: bytes = b"") -> Receipt:
        with self._lock:
            events_before = len(self.smc.events)
            self.smc.add_header(sender, shard_id, period, chunk_root,
                                signature, self.pending_block_number)
            return self._record(events_before)

    def submit_vote(self, sender: Address20, shard_id: int, period: int,
                    index: int, chunk_root: Hash32) -> Receipt:
        with self._lock:
            events_before = len(self.smc.events)
            self.smc.submit_vote(sender, shard_id, period, index, chunk_root,
                                 self.pending_block_number)
            return self._record(events_before)

    # -- SMC view surface (latest sealed block, like eth_call) ------------

    def get_notary_in_committee(self, sender: Address20, shard_id: int) -> Address20:
        return self.smc.get_notary_in_committee_view(
            sender, shard_id, self.block_number
        )

    def notary_registry(self, address: Address20):
        return self.smc.notary_registry.get(address)

    def collation_record(self, shard_id: int, period: int):
        return self.smc.collation_records.get((shard_id, period))

    def last_submitted_collation(self, shard_id: int) -> int:
        return self.smc.last_submitted_collation.get(shard_id, 0)

    def last_approved_collation(self, shard_id: int) -> int:
        return self.smc.last_approved_collation.get(shard_id, 0)
