"""Consensus engines for the dev mainchain (`consensus/consensus.go` role).

The reference pluggs a `consensus.Engine` into its blockchain — ethash
PoW (`consensus/ethash/sealer.go`: nonce-space search), clique PoA
(`consensus/clique/clique.go`: signer rotation + in-extra signatures +
signer voting), and the "fake" engine every dev/simulated chain runs on
(`consensus/ethash/ethash.go` ModeFake). The sharding layer itself never
consumes an engine (consensus lives in the SMC), but the mainchain the
actors talk to does; this module gives `smc/chain.py` the same seam.

Engines here follow the same split the reference's interface draws
(`consensus/consensus.go:47-80`): `seal` produces the next sealed block
from a parent, `verify_header` checks a block received from elsewhere
(the `import_chain` path), and `finalize`/`snapshot`/`restore` carry any
engine-held state (clique's vote tallies) across the chain's rollback
machinery. Blocks stay the dev chain's empty-body headers: an engine
decides only the `extra` payload and the hash rule.

Design note (TPU-first repo): sealing is a host-side concern — a few
keccaks per block — and stays scalar Python; nothing here runs on
device. The engines exist for capability parity and for exercising the
import/reorg path with real verification rules.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.utils.hexbytes import Address20, Hash32
from gethsharding_tpu.utils.rlp import int_to_big_endian, rlp_encode


class InvalidHeader(Exception):
    """A block failed engine verification (consensus.ErrInvalidHeader)."""


def _header_rlp(number: int, parent_hash: Hash32, extra: bytes) -> bytes:
    return rlp_encode([int_to_big_endian(number), bytes(parent_hash), extra])


class FakeEngine:
    """ModeFake: no seal work, hash over [number, parent] only.

    Byte-compatible with the pre-engine dev chain (`smc/chain.py`
    `_block_hash`): the empty-extra hash omits the extra field entirely,
    so every existing frozen block-hash vector still holds.
    """

    name = "fake"

    def seal(self, number: int, parent_hash: Hash32) -> Tuple[Hash32, bytes]:
        return self.hash_header(number, parent_hash, b""), b""

    def hash_header(self, number: int, parent_hash: Hash32,
                    extra: bytes) -> Hash32:
        if extra:
            return Hash32(keccak256(_header_rlp(number, parent_hash, extra)))
        return Hash32(keccak256(rlp_encode([int_to_big_endian(number),
                                            bytes(parent_hash)])))

    def verify_header(self, number: int, parent_hash: Hash32, extra: bytes,
                      block_hash: Hash32) -> None:
        if bytes(self.hash_header(number, parent_hash, extra)) != bytes(block_hash):
            raise InvalidHeader(f"block {number}: hash mismatch")

    def finalize(self, number: int, parent_hash: Hash32, extra: bytes) -> None:
        pass

    def snapshot(self):
        return None

    def restore(self, state) -> None:
        pass


class DevPoWEngine(FakeEngine):
    """A DAG-less dev PoW: nonce search until keccak(header) clears a
    difficulty target (the `consensus/ethash/sealer.go:113` nonce loop
    with hashimoto replaced by plain keccak — the DAG is a memory-hard
    anti-ASIC artifact with no behavioral role for a dev chain, and is
    descoped per SURVEY.md §2.3)."""

    name = "devpow"

    def __init__(self, difficulty_bits: int = 8):
        if not 0 <= difficulty_bits <= 64:
            raise ValueError("difficulty_bits out of range")
        self.difficulty_bits = difficulty_bits

    def _meets_target(self, digest: bytes) -> bool:
        work = int.from_bytes(digest[:8], "big")
        return work >> (64 - self.difficulty_bits) == 0 \
            if self.difficulty_bits else True

    def seal(self, number: int, parent_hash: Hash32) -> Tuple[Hash32, bytes]:
        nonce = 0
        while True:
            extra = nonce.to_bytes(8, "big")
            digest = keccak256(_header_rlp(number, parent_hash, extra))
            if self._meets_target(digest):
                return Hash32(digest), extra
            nonce += 1

    def hash_header(self, number: int, parent_hash: Hash32,
                    extra: bytes) -> Hash32:
        return Hash32(keccak256(_header_rlp(number, parent_hash, extra)))

    def verify_header(self, number: int, parent_hash: Hash32, extra: bytes,
                      block_hash: Hash32) -> None:
        if len(extra) != 8:
            raise InvalidHeader(f"block {number}: PoW nonce must be 8 bytes")
        digest = keccak256(_header_rlp(number, parent_hash, extra))
        if bytes(digest) != bytes(block_hash):
            raise InvalidHeader(f"block {number}: hash mismatch")
        if not self._meets_target(digest):
            raise InvalidHeader(f"block {number}: insufficient work")


@dataclass
class _Vote:
    """One pending authorization vote (clique.Vote)."""

    signer: Address20
    target: Address20
    authorize: bool


class CliqueEngine:
    """Proof-of-authority with signer rotation, in-extra seals and
    majority signer voting (`consensus/clique/clique.go`).

    Kept rules:
      - the seal is a 65-byte secp256k1 signature over the header with
        the signature itself excluded (clique.go sigHash / SealHash);
      - the sealer must be an authorized signer, and must be IN TURN
        (`signers[number % len(signers)]` over the sorted set) — the dev
        chain seals on demand, so the out-of-turn/wiggle path
        (clique.go:581) would never be exercised and is rejected
        outright rather than merely de-prioritized;
      - a seal may carry one authorization proposal (20-byte target +
        0x00/0xff drop/add, the coinbase+nonce encoding of
        clique.go:283 collapsed into the extra field); a strict majority
        of current signers adopts it, clearing that target's tally;
      - every `epoch` blocks all pending votes reset (clique.go:416).

    Engine state (signer set + tallies) is chain state in geth
    (recomputed from headers via snapshots); here the chain's own
    snapshot ring carries it through rollbacks via snapshot()/restore().
    """

    name = "clique"
    EPOCH = 30

    def __init__(self, signers: Sequence[Address20], epoch: int = EPOCH):
        if not signers:
            raise ValueError("clique needs at least one signer")
        self._signers: List[bytes] = sorted({bytes(s) for s in signers})
        self._votes: List[_Vote] = []
        self.epoch = epoch
        self._lock = threading.RLock()
        self._sign_fn = None
        self._bound_signer: Optional[Address20] = None
        self._pending_proposal: Optional[Tuple[Address20, bool]] = None
        self._recover_memo: Dict[tuple, Address20] = {}

    def bind_sealer(self, sign_fn, signer: Address20) -> None:
        """Attach this node's keystore signer (clique.Authorize,
        clique.go:590). Required before the chain can seal blocks."""
        self._sign_fn = sign_fn
        self._bound_signer = signer

    def propose(self, target: Address20, authorize: bool) -> None:
        """Queue an authorization proposal for the next sealed block
        (the `clique_propose` RPC, api.go:66)."""
        self._pending_proposal = (target, authorize)

    # -- signer set --------------------------------------------------------

    def signers(self) -> List[Address20]:
        with self._lock:
            return [Address20(s) for s in self._signers]

    def in_turn_signer(self, number: int) -> Address20:
        with self._lock:
            return Address20(self._signers[number % len(self._signers)])

    # -- sealing -----------------------------------------------------------

    @staticmethod
    def _encode_proposal(proposal: Optional[Tuple[Address20, bool]]) -> bytes:
        if proposal is None:
            return b""
        target, authorize = proposal
        return bytes(target) + (b"\xff" if authorize else b"\x00")

    def seal_hash(self, number: int, parent_hash: Hash32,
                  vanity: bytes) -> Hash32:
        """Digest the seal signs: header with the signature excluded
        (clique.go SealHash)."""
        return Hash32(keccak256(_header_rlp(number, parent_hash, vanity)))

    def seal(self, number: int, parent_hash: Hash32) -> Tuple[Hash32, bytes]:
        """Seal with the bound keystore signer, consuming any queued
        proposal (the uniform engine interface `smc/chain.py` drives)."""
        with self._lock:
            sign_fn, signer = self._sign_fn, self._bound_signer
            proposal = self._pending_proposal
        if sign_fn is None or signer is None:
            raise InvalidHeader("clique engine has no bound sealer "
                                "(call bind_sealer first)")
        result = self.seal_as(number, parent_hash, sign_fn=sign_fn,
                              signer=signer, proposal=proposal)
        with self._lock:
            # consume only on success: a failed seal (e.g. out of turn)
            # keeps the queued clique_propose for the next block
            if self._pending_proposal == proposal:
                self._pending_proposal = None
        return result

    def seal_as(self, number: int, parent_hash: Hash32, *,
                sign_fn, signer: Address20,
                proposal: Optional[Tuple[Address20, bool]] = None,
                ) -> Tuple[Hash32, bytes]:
        """Produce (hash, extra). `sign_fn(digest) -> 65-byte [R||S||V]`
        is the keystore seam (accounts.AccountManager.sign_hash)."""
        with self._lock:
            if bytes(signer) not in self._signers:
                raise InvalidHeader("unauthorized signer")
            if bytes(signer) != bytes(self.in_turn_signer(number)):
                raise InvalidHeader(
                    f"signer not in turn for block {number}")
        vanity = self._encode_proposal(proposal)
        sig = sign_fn(bytes(self.seal_hash(number, parent_hash, vanity)))
        if len(sig) != 65:
            raise InvalidHeader("seal signature must be 65 bytes")
        extra = vanity + sig
        return self.hash_header(number, parent_hash, extra), extra

    def hash_header(self, number: int, parent_hash: Hash32,
                    extra: bytes) -> Hash32:
        return Hash32(keccak256(_header_rlp(number, parent_hash, extra)))

    # -- verification ------------------------------------------------------

    def _split_extra(self, number: int, extra: bytes
                     ) -> Tuple[bytes, bytes]:
        if len(extra) == 65:
            return b"", extra
        if len(extra) == 21 + 65:
            if extra[20] not in (0x00, 0xFF):
                # only the two flag values the encoder emits are valid
                # votes (clique.go errInvalidVote)
                raise InvalidHeader(
                    f"block {number}: invalid vote flag 0x{extra[20]:02x}")
            return extra[:21], extra[21:]
        raise InvalidHeader(f"block {number}: malformed clique extra "
                            f"({len(extra)} bytes)")

    def recover_signer(self, number: int, parent_hash: Hash32,
                       extra: bytes) -> Address20:
        # verify_header and finalize both need the sealer of the same
        # block back to back (import path: verify then finalize; seal
        # path: the chain finalizes a seal it just produced) — memoize
        # the last few recoveries so adoption costs ONE ecrecover
        key = (number, bytes(parent_hash), extra)
        with self._lock:
            cached = self._recover_memo.get(key)
        if cached is not None:
            return cached
        vanity, sig = self._split_extra(number, extra)
        digest = bytes(self.seal_hash(number, parent_hash, vanity))
        try:
            signature = secp256k1.Signature.from_bytes65(sig)
            sealer = secp256k1.ecrecover_address(digest, signature)
        except (ValueError, ArithmeticError) as exc:
            raise InvalidHeader(f"block {number}: bad seal: {exc}") from exc
        with self._lock:
            self._recover_memo[key] = sealer
            # big enough that an import's verify walk still covers its
            # finalize replay (branches re-verify then re-finalize)
            while len(self._recover_memo) > 256:
                self._recover_memo.pop(next(iter(self._recover_memo)))
        return sealer

    def verify_header(self, number: int, parent_hash: Hash32, extra: bytes,
                      block_hash: Hash32) -> None:
        if bytes(self.hash_header(number, parent_hash, extra)) \
                != bytes(block_hash):
            raise InvalidHeader(f"block {number}: hash mismatch")
        sealer = self.recover_signer(number, parent_hash, extra)
        with self._lock:
            if bytes(sealer) not in self._signers:
                raise InvalidHeader(
                    f"block {number}: unauthorized signer "
                    f"{sealer.hex_str}")
            if bytes(sealer) != bytes(self.in_turn_signer(number)):
                raise InvalidHeader(f"block {number}: signer out of turn")

    # -- state transitions (applied on adoption, seal AND import) ----------

    def finalize(self, number: int, parent_hash: Hash32,
                 extra: bytes) -> None:
        """Apply an adopted block's authorization vote, if any, and the
        epoch reset (clique.go snapshot.apply)."""
        with self._lock:
            if self.epoch and number % self.epoch == 0:
                self._votes.clear()
            vanity, _ = self._split_extra(number, extra)
            if not vanity:
                return
            sealer = self.recover_signer(number, parent_hash, extra)
            target = Address20(vanity[:20])
            authorize = vanity[20] == 0xFF
            already = bytes(target) in self._signers
            if authorize == already:
                return  # no-op proposal (clique.go validVote)
            # one live vote per (signer, target): latest wins
            self._votes = [v for v in self._votes
                           if not (bytes(v.signer) == bytes(sealer)
                                   and bytes(v.target) == bytes(target))]
            self._votes.append(_Vote(sealer, target, authorize))
            tally = sum(1 for v in self._votes
                        if bytes(v.target) == bytes(target)
                        and v.authorize == authorize)
            if tally > len(self._signers) // 2:
                if authorize:
                    self._signers = sorted(self._signers + [bytes(target)])
                elif len(self._signers) == 1:
                    # a drop that would empty the signer set would wedge
                    # the chain (nobody could ever seal again); discard
                    # the tally instead
                    self._votes = [v for v in self._votes
                                   if bytes(v.target) != bytes(target)]
                    return
                else:
                    self._signers.remove(bytes(target))
                    # a dropped signer's outstanding votes die with it
                    self._votes = [v for v in self._votes
                                   if bytes(v.signer) != bytes(target)]
                self._votes = [v for v in self._votes
                               if bytes(v.target) != bytes(target)]

    # -- rollback support --------------------------------------------------

    def snapshot(self):
        with self._lock:
            return (list(self._signers),
                    [(bytes(v.signer), bytes(v.target), v.authorize)
                     for v in self._votes])

    def restore(self, state) -> None:
        signers, votes = state
        with self._lock:
            self._signers = list(signers)
            self._votes = [_Vote(Address20(s), Address20(t), a)
                           for s, t, a in votes]
