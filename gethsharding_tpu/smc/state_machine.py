"""SMC: the Sharding Manager Contract as a deterministic state machine.

Semantics-parity reimplementation of `sharding/contracts/sharding_manager.sol`
(every rule cited below by .sol line). The EVM is deliberately absent: the
framework's own consensus hub is a native transition system whose outcomes
(vote bitfields, committee sampling, quorum flips) are required to be
byte-identical with what the Solidity contract would compute, including its
quirks:

- the vote word packs a 255-bit bitfield (bit `255 - index`) plus a count in
  the low byte (.sol:32-34, castVote :276);
- `stackPop` requires the stack top to be > 1, so the last freed pool slot
  is never reused (.sol:262 `require(emptySlotsStackTop > 1)`);
- committee sampling is `keccak256(bytes32(blockhash) ++ bytes32(poolIndex)
  ++ bytes32(shardId)) % sampleSize` over the last block of the previous
  period (.sol:90-99), with the sample size tracked one period ahead
  (updateNotarySampleSize :250).

Every method takes the acting `block_number` explicitly — there is no
ambient chain context — so the machine is replayable and testable in
isolation, and the fixed-shape TPU form (`gethsharding_tpu.ops.smc_jax`)
can be differential-tested against it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from gethsharding_tpu.crypto import bn256
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.params import Config, DEFAULT_CONFIG
from gethsharding_tpu.utils.hexbytes import Address20, Hash32

UINT256_MASK = (1 << 256) - 1


class SMCRevert(Exception):
    """Equivalent of a failed Solidity `require` — the tx has no effect."""


def vote_digest(shard_id: int, period: int, chunk_root: Hash32) -> bytes:
    """The message a notary BLS-signs when voting: domain-separated
    (shard, period, chunkRoot) tuple. Same-message aggregation per shard —
    every committee member of a shard signs the identical digest, so the
    period pipeline verifies ONE aggregate pair per shard.

    (TPU-native extension over `sharding_manager.sol:198-221`, where vote
    authenticity rides only on the tx sender; here votes additionally
    carry an aggregatable signature so validators can batch-verify whole
    periods in one device dispatch — the north-star hot loop.)
    """
    return keccak256(
        b"gethsharding-vote-v1/"
        + shard_id.to_bytes(32, "big")
        + period.to_bytes(32, "big")
        + bytes(chunk_root)
    )


@dataclass
class Notary:
    """Per-notary registry entry (.sol:11-16), extended with the BLS vote
    pubkey registered alongside the deposit (PoP retained for batch
    verification by validators — rogue-key defense)."""

    deregistered_period: int = 0
    pool_index: int = 0
    balance: int = 0
    deposited: bool = False
    bls_pubkey: Optional[bn256.G2Point] = None
    bls_pop: Optional[bn256.G1Point] = None


@dataclass
class VoteSig:
    """An accepted vote's BLS signature with signer attribution, recorded
    at vote time so the period audit resolves the voter's registered
    pubkey WITHOUT consulting the live pool (pool slots can be freed and
    reused between the vote and the audit)."""

    sig: bn256.G1Point
    signer: Address20


@dataclass
class CollationRecord:
    """Per-(shard, period) collation header record (.sol:18-23), extended
    with the accepted votes' BLS signatures keyed by committee bitfield
    index — the persistent artifact the batched period audit verifies —
    and a persistent accepted-vote counter (the packed word's low byte is
    transient: addHeader clears it next period, .sol:187)."""

    chunk_root: Hash32 = field(default_factory=Hash32)
    proposer: Address20 = field(default_factory=Address20)
    is_elected: bool = False
    signature: bytes = b""
    vote_sigs: Dict[int, VoteSig] = field(default_factory=dict)
    vote_count: int = 0


@dataclass
class Event:
    name: str
    args: dict


class SMC:
    """The contract state + transition rules.

    `blockhash_fn(number) -> Hash32` supplies mainchain block hashes for
    committee sampling (the `block.blockhash` dependency).
    """

    def __init__(self, config: Config = DEFAULT_CONFIG,
                 blockhash_fn: Optional[Callable[[int], Hash32]] = None):
        self.config = config
        self.blockhash_fn = blockhash_fn or (lambda n: Hash32())

        # notary state (.sol:25-34)
        self.notary_pool: List[Optional[Address20]] = []
        self.notary_registry: Dict[Address20, Notary] = {}
        self.notary_pool_length: int = 0
        self.current_vote: Dict[int, int] = {}  # shard -> packed uint256

        # collation state (.sol:36-42)
        self.collation_records: Dict[Tuple[int, int], CollationRecord] = {}
        self.last_submitted_collation: Dict[int, int] = {}
        self.last_approved_collation: Dict[int, int] = {}

        # empty-slot stack + sample-size bookkeeping (.sol:44-52)
        self.empty_slots_stack: List[int] = []
        self.empty_slots_stack_top: int = 0
        self.current_period_notary_sample_size: int = 0
        self.next_period_notary_sample_size: int = 0
        self.sample_size_last_updated_period: int = 0

        self.shard_count: int = config.shard_count
        self.balance: int = 0  # ether held by the contract
        self.events: List[Event] = []

    # -- internal helpers --------------------------------------------------

    def _period(self, block_number: int) -> int:
        return block_number // self.config.period_length

    def _update_notary_sample_size(self, block_number: int) -> None:
        """updateNotarySampleSize (.sol:250-258)."""
        current_period = self._period(block_number)
        if current_period < self.sample_size_last_updated_period:
            return
        self.current_period_notary_sample_size = self.next_period_notary_sample_size
        self.sample_size_last_updated_period = current_period

    def _stack_empty(self) -> bool:
        return self.empty_slots_stack_top == 0

    def _stack_push(self, index: int) -> None:
        if len(self.empty_slots_stack) == self.empty_slots_stack_top:
            self.empty_slots_stack.append(index)
        else:
            self.empty_slots_stack[self.empty_slots_stack_top] = index
        self.empty_slots_stack_top += 1

    def _stack_pop(self) -> int:
        # reference quirk preserved: the last freed slot is unreachable
        # (.sol:262 `require(emptySlotsStackTop > 1)`)
        if not self.empty_slots_stack_top > 1:
            raise SMCRevert("stackPop: emptySlotsStackTop <= 1")
        self.empty_slots_stack_top -= 1
        return self.empty_slots_stack[self.empty_slots_stack_top]

    # -- views -------------------------------------------------------------

    def get_notary_in_committee(self, sender: Address20, shard_id: int,
                                block_number: int) -> Address20:
        """Committee sampling (.sol:77-100).

        NOTE: mirrors the mutating-view quirk — the Solidity function calls
        updateNotarySampleSize() even though it is marked `view` (a no-op
        on-chain via STATICCALL for eth_call, but state-changing inside a
        transaction such as submitVote). We therefore only mutate when used
        inside a transaction; pure view usage passes `mutate=False` via
        get_notary_in_committee_view.
        """
        return self._committee_member(sender, shard_id, block_number, mutate=True)

    def get_notary_in_committee_view(self, sender: Address20, shard_id: int,
                                     block_number: int) -> Address20:
        return self._committee_member(sender, shard_id, block_number, mutate=False)

    def _committee_member(self, sender: Address20, shard_id: int,
                          block_number: int, mutate: bool) -> Address20:
        period = self._period(block_number)
        if mutate:
            self._update_notary_sample_size(block_number)
            sample_size_last_updated = self.sample_size_last_updated_period
            current_size = self.current_period_notary_sample_size
            next_size = self.next_period_notary_sample_size
        else:
            # simulate the sample-size update without committing it
            sample_size_last_updated = self.sample_size_last_updated_period
            current_size = self.current_period_notary_sample_size
            next_size = self.next_period_notary_sample_size
            if period >= sample_size_last_updated:
                current_size = next_size
                sample_size_last_updated = period

        if period > sample_size_last_updated:
            sample_size = next_size
        else:
            sample_size = current_size

        registry_entry = self.notary_registry.get(sender, Notary())
        pool_index = registry_entry.pool_index

        latest_block = period * self.config.period_length - 1
        latest_block_hash = self.blockhash_fn(latest_block)
        preimage = (
            bytes(latest_block_hash)
            + pool_index.to_bytes(32, "big")
            + shard_id.to_bytes(32, "big")
        )
        index = int.from_bytes(keccak256(preimage), "big")
        if sample_size == 0:
            raise SMCRevert("committee sample size is zero (division by zero)")
        index %= sample_size
        member = self.notary_pool[index] if index < len(self.notary_pool) else None
        return member if member is not None else Address20()

    def get_vote_count(self, shard_id: int) -> int:
        """Low byte of the packed vote word (.sol:224-229)."""
        return self.current_vote.get(shard_id, 0) % 256

    def has_voted(self, shard_id: int, index: int) -> bool:
        """Bit `255 - index` of the packed vote word (.sol:233-239)."""
        votes = self.current_vote.get(shard_id, 0)
        return (votes >> (255 - index)) & 1 == 1

    # -- transactions ------------------------------------------------------

    def register_notary(self, sender: Address20, value: int,
                        block_number: int,
                        bls_pubkey: Optional[bn256.G2Point] = None,
                        bls_pop: Optional[bn256.G1Point] = None) -> None:
        """registerNotary (.sol:103-133). `bls_pubkey`/`bls_pop` register
        the notary's aggregatable vote key; when a pubkey is supplied a PoP
        must accompany it (its pairing check is deferred to the batched
        validator audit, keeping registration scalar-crypto-free)."""
        entry = self.notary_registry.get(sender)
        if entry is not None and entry.deposited:
            raise SMCRevert("notary already deposited")
        if value != self.config.notary_deposit:
            raise SMCRevert("deposit must be exactly NOTARY_DEPOSIT")
        if bls_pubkey is not None and bls_pop is None:
            raise SMCRevert("BLS pubkey requires a proof of possession")

        self._update_notary_sample_size(block_number)

        if self._stack_empty():
            index = self.notary_pool_length
            self.notary_pool.append(sender)
        else:
            index = self._stack_pop()
            self.notary_pool[index] = sender
        self.notary_pool_length += 1

        self.notary_registry[sender] = Notary(
            deregistered_period=0, pool_index=index, balance=value,
            deposited=True, bls_pubkey=bls_pubkey, bls_pop=bls_pop,
        )
        self.balance += value

        if index >= self.next_period_notary_sample_size:
            self.next_period_notary_sample_size = index + 1

        self.events.append(
            Event("NotaryRegistered", {"notary": sender, "poolIndex": index})
        )

    def deregister_notary(self, sender: Address20, block_number: int) -> None:
        """deregisterNotary (.sol:138-154)."""
        entry = self.notary_registry.get(sender)
        if entry is None or not entry.deposited:
            raise SMCRevert("notary not deposited")
        index = entry.pool_index
        if index >= len(self.notary_pool) or self.notary_pool[index] != sender:
            raise SMCRevert("pool entry does not match sender")

        self._update_notary_sample_size(block_number)

        deregistered_period = self._period(block_number)
        entry.deregistered_period = deregistered_period
        self._stack_push(index)
        self.notary_pool[index] = None  # `delete notaryPool[index]`
        self.notary_pool_length -= 1
        self.events.append(
            Event(
                "NotaryDeregistered",
                {"notary": sender, "poolIndex": index,
                 "deregisteredPeriod": deregistered_period},
            )
        )

    def release_notary(self, sender: Address20, block_number: int) -> int:
        """releaseNotary (.sol:157-168); returns the released balance."""
        entry = self.notary_registry.get(sender)
        if entry is None or entry.deposited is not True:
            raise SMCRevert("notary not deposited")
        if entry.deregistered_period == 0:
            raise SMCRevert("notary has not deregistered")
        if not (self._period(block_number)
                > entry.deregistered_period + self.config.notary_lockup_length):
            raise SMCRevert("lockup period not over")

        index = entry.pool_index
        balance = entry.balance
        del self.notary_registry[sender]
        self.balance -= balance
        self.events.append(
            Event("NotaryReleased", {"notary": sender, "poolIndex": index})
        )
        return balance

    def add_header(self, sender: Address20, shard_id: int, period: int,
                   chunk_root: Hash32, signature: bytes,
                   block_number: int) -> None:
        """addHeader (.sol:171-195)."""
        if not (0 <= shard_id < self.shard_count):
            raise SMCRevert("shard id out of range")
        if period != self._period(block_number):
            raise SMCRevert("period is not current")
        if period <= self.last_submitted_collation.get(shard_id, 0):
            raise SMCRevert("period already has a submitted collation")

        self._update_notary_sample_size(block_number)

        self.collation_records[(shard_id, period)] = CollationRecord(
            chunk_root=Hash32(chunk_root),
            proposer=sender,
            is_elected=False,
            signature=bytes(signature),
        )
        self.last_submitted_collation[shard_id] = self._period(block_number)
        self.current_vote.pop(shard_id, None)  # `delete currentVote[_shardId]`
        self.events.append(
            Event(
                "HeaderAdded",
                {"shardId": shard_id, "chunkRoot": Hash32(chunk_root),
                 "period": period, "proposerAddress": sender},
            )
        )

    def submit_vote(self, sender: Address20, shard_id: int, period: int,
                    index: int, chunk_root: Hash32, block_number: int,
                    bls_sig: Optional[bn256.G1Point] = None) -> None:
        """submitVote (.sol:198-221), extended: a notary registered with a
        BLS pubkey must attach its signature over
        `vote_digest(shard, period, chunkRoot)`. Authenticity within a tx
        still rides on the sender (reference parity); the stored signature
        is the artifact the batched period audit verifies in one device
        dispatch — an invalid one is detected there (and in a slashing
        design would forfeit the deposit)."""
        if not (0 <= shard_id < self.shard_count):
            raise SMCRevert("shard id out of range")
        if period != self._period(block_number):
            raise SMCRevert("period is not current")
        if period != self.last_submitted_collation.get(shard_id, 0):
            raise SMCRevert("no collation submitted this period")
        if not index < self.config.committee_size:
            raise SMCRevert("index out of committee range")
        record = self.collation_records.get((shard_id, period))
        if record is None or Hash32(chunk_root) != record.chunk_root:
            raise SMCRevert("chunk root does not match submitted collation")
        entry = self.notary_registry.get(sender)
        if entry is None or not entry.deposited:
            raise SMCRevert("sender is not a deposited notary")
        if entry.bls_pubkey is not None:
            if bls_sig is None:
                raise SMCRevert("vote must carry a BLS signature")
            # the reference contract leaves _index unbound to the sender
            # (.sol:198-221 checks only range + hasVoted); for SIGNED votes
            # the index is the attribution key, so it must be the sender's
            # own pool slot — otherwise a voter could burn another slot's
            # bit and poison the audit's signer resolution
            if index != entry.pool_index:
                raise SMCRevert(
                    "signed vote index must be the sender's pool index")
        if self.has_voted(shard_id, index):
            raise SMCRevert("notary already voted at this index")
        if self.get_notary_in_committee(sender, shard_id, block_number) != sender:
            raise SMCRevert("sender is not the sampled committee member")

        self._cast_vote(shard_id, index)
        record.vote_count += 1
        if bls_sig is not None:
            record.vote_sigs[index] = VoteSig(sig=bls_sig, signer=sender)
        vote_count = self.get_vote_count(shard_id)
        if vote_count >= self.config.quorum_size:
            self.last_approved_collation[shard_id] = period
            record.is_elected = True
        self.events.append(
            Event(
                "VoteSubmitted",
                {"shardId": shard_id, "chunkRoot": Hash32(chunk_root),
                 "period": period, "notaryAddress": sender},
            )
        )

    def _cast_vote(self, shard_id: int, index: int) -> None:
        """castVote (.sol:276-285): set bit 255-index, then increment count."""
        votes = self.current_vote.get(shard_id, 0)
        votes |= 1 << (255 - index)
        votes = (votes + 1) & UINT256_MASK
        self.current_vote[shard_id] = votes
