"""Narrow role interfaces between actors and the mainchain.

Parity: `sharding/mainchain/interfaces.go:16-68` (Signer, ContractCaller,
ContractTransactor, EthClient/Reader). Actors depend on these protocols —
never on a concrete backend — which is exactly what makes fault-injection
test doubles possible (SURVEY.md §4).
"""

from __future__ import annotations

from typing import Optional, Protocol, runtime_checkable

from gethsharding_tpu.utils.hexbytes import Address20, Hash32


@runtime_checkable
class Signer(Protocol):
    """Sign a 32-byte hash with the node account (smc_client.go:245)."""

    def sign(self, digest: bytes) -> bytes: ...

    def account(self) -> Address20: ...


@runtime_checkable
class ChainReader(Protocol):
    """Head subscriptions + block access (ethclient Reader surface)."""

    def subscribe_new_head(self, callback): ...

    def block_by_number(self, number: Optional[int] = None): ...

    @property
    def block_number(self) -> int: ...


@runtime_checkable
class ContractCaller(Protocol):
    """SMC view calls (SMCCaller surface)."""

    def get_notary_in_committee(self, sender: Address20, shard_id: int) -> Address20: ...

    def notary_registry(self, address: Address20): ...

    def collation_record(self, shard_id: int, period: int): ...

    def last_submitted_collation(self, shard_id: int) -> int: ...

    def last_approved_collation(self, shard_id: int) -> int: ...


@runtime_checkable
class ContractTransactor(Protocol):
    """SMC transactions (SMCTransactor surface)."""

    def register_notary(self, sender: Address20, value: Optional[int] = None): ...

    def deregister_notary(self, sender: Address20): ...

    def release_notary(self, sender: Address20): ...

    def add_header(self, sender: Address20, shard_id: int, period: int,
                   chunk_root: Hash32, signature: bytes = b""): ...

    def submit_vote(self, sender: Address20, shard_id: int, period: int,
                    index: int, chunk_root: Hash32): ...
