"""Account management: the keystore seam.

Parity target: `accounts/keystore` as used by SMCClient
(`sharding/mainchain/smc_client.go:218` unlockAccount, :245 Sign). This
in-memory manager holds secp256k1 keys with unlock semantics; the
encrypted on-disk keystore (scrypt + AES-CTR JSON files) layers on top in
`gethsharding_tpu.mainchain.keystore`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.utils.hexbytes import Address20


@dataclass
class Account:
    address: Address20
    priv: int
    unlocked: bool = False


class AccountManager:
    """Holds accounts; signing requires an unlocked account."""

    def __init__(self):
        self._accounts: Dict[Address20, Account] = {}

    def new_account(self, seed: bytes = b"", unlock: bool = True) -> Account:
        if seed:
            priv = int.from_bytes(keccak256(b"key" + seed), "big") % secp256k1.N
            priv = priv or 1
        else:
            import secrets

            priv = secrets.randbelow(secp256k1.N - 1) + 1
        account = Account(
            address=secp256k1.priv_to_address(priv), priv=priv, unlocked=unlock
        )
        self._accounts[account.address] = account
        return account

    def import_key(self, priv: int, unlock: bool = True) -> Account:
        account = Account(
            address=secp256k1.priv_to_address(priv), priv=priv, unlocked=unlock
        )
        self._accounts[account.address] = account
        return account

    def unlock(self, address: Address20) -> None:
        self._accounts[address].unlocked = True

    def lock(self, address: Address20) -> None:
        self._accounts[address].unlocked = False

    def get(self, address: Address20) -> Optional[Account]:
        return self._accounts.get(address)

    def sign_hash(self, address: Address20, digest: bytes) -> bytes:
        account = self._accounts.get(address)
        if account is None:
            raise KeyError(f"unknown account {address.hex_str}")
        if not account.unlocked:
            raise PermissionError(f"account {address.hex_str} is locked")
        return secp256k1.sign(digest, account.priv).to_bytes65()
