"""Account management: the keystore seam.

Parity target: `accounts/keystore` as used by SMCClient
(`sharding/mainchain/smc_client.go:218` unlockAccount, :245 Sign). This
in-memory manager holds secp256k1 keys with unlock semantics; the
encrypted on-disk keystore (scrypt + AES-CTR JSON files) layers on top in
`gethsharding_tpu.mainchain.keystore`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from gethsharding_tpu.crypto import bn256, secp256k1
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.utils.hexbytes import Address20


@dataclass
class Account:
    address: Address20
    priv: int
    unlocked: bool = False
    # BLS vote keypair, derived deterministically from the secp256k1 key
    # (one identity, two signature schemes: ECDSA for transactions, BLS for
    # aggregatable committee votes — BASELINE.md configs 2-3)
    _bls: Optional[Tuple[int, bn256.G2Point]] = field(
        default=None, repr=False, compare=False)

    def bls_keypair(self) -> Tuple[int, bn256.G2Point]:
        if self._bls is None:
            self._bls = bn256.bls_keygen(self.priv.to_bytes(32, "big"))
        return self._bls

    @property
    def bls_pubkey(self) -> bn256.G2Point:
        return self.bls_keypair()[1]


class AccountManager:
    """Holds accounts; signing requires an unlocked account."""

    def __init__(self):
        self._accounts: Dict[Address20, Account] = {}

    def new_account(self, seed: bytes = b"", unlock: bool = True) -> Account:
        if seed:
            priv = int.from_bytes(keccak256(b"key" + seed), "big") % secp256k1.N
            priv = priv or 1
        else:
            import secrets

            priv = secrets.randbelow(secp256k1.N - 1) + 1
        account = Account(
            address=secp256k1.priv_to_address(priv), priv=priv, unlocked=unlock
        )
        self._accounts[account.address] = account
        return account

    def import_key(self, priv: int, unlock: bool = True) -> Account:
        account = Account(
            address=secp256k1.priv_to_address(priv), priv=priv, unlocked=unlock
        )
        self._accounts[account.address] = account
        return account

    def unlock(self, address: Address20) -> None:
        self._accounts[address].unlocked = True

    def lock(self, address: Address20) -> None:
        self._accounts[address].unlocked = False

    def get(self, address: Address20) -> Optional[Account]:
        return self._accounts.get(address)

    def sign_hash(self, address: Address20, digest: bytes) -> bytes:
        account = self._require_unlocked(address)
        return secp256k1.sign(digest, account.priv).to_bytes65()

    def bls_sign(self, address: Address20, message: bytes) -> bn256.G1Point:
        """BLS-sign a vote message with the account's derived vote key."""
        account = self._require_unlocked(address)
        sk, _ = account.bls_keypair()
        return bn256.bls_sign(message, sk)

    def bls_proof_of_possession(self, address: Address20) -> bn256.G1Point:
        """PoP binding the vote pubkey to its secret key (rogue-key defense;
        verified in batch by the notary audit pipeline, not per-tx)."""
        account = self._require_unlocked(address)
        sk, pk = account.bls_keypair()
        return bn256.bls_prove_possession(sk, pk)

    def _require_unlocked(self, address: Address20) -> Account:
        account = self._accounts.get(address)
        if account is None:
            raise KeyError(f"unknown account {address.hex_str}")
        if not account.unlocked:
            raise PermissionError(f"account {address.hex_str} is locked")
        return account
