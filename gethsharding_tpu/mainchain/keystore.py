"""Encrypted on-disk keystore: scrypt + AES-128-CTR JSON key files.

Parity target: `accounts/keystore` (geth Web3 Secret Storage version 3 —
`keystore.go:79`, `passphrase.go` EncryptKey/DecryptKey) as consumed by the
sharding client's unlock flow (`sharding/mainchain/smc_client.go:218`).
Files written here use the same JSON schema, KDF, cipher, and keccak-based
MAC as geth's, so keys round-trip between the two implementations. The
default scrypt cost is geth's "standard" profile (n=262144, r=8, p=1);
tests use light parameters for speed.

Identity persistence: `Keystore.load_or_create` gives a node a stable
address across restarts from `<datadir>/keystore` + a password (the
`--datadir`/`--password` flow in `node/cli.py`).
"""

from __future__ import annotations

import json
import os
import secrets
import time
from dataclasses import dataclass
from hashlib import scrypt
from pathlib import Path
from typing import List, Optional

from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.utils.hexbytes import Address20

STANDARD_SCRYPT_N = 262144
STANDARD_SCRYPT_P = 1
LIGHT_SCRYPT_N = 4096
LIGHT_SCRYPT_P = 6
SCRYPT_R = 8
SCRYPT_DKLEN = 32


class KeystoreError(Exception):
    pass


def scrypt_kdf(password: bytes, salt: bytes, n: int, r: int, p: int,
               dklen: int) -> bytes:
    """scrypt that accepts EVERY parameter set geth's Go scrypt does.

    OpenSSL (hashlib.scrypt) enforces the RFC's N < 2^(128*r/8) bound,
    rejecting the Web3 Secret Storage wiki/light profile (n=262144, r=1,
    p=8) — real key files use it, and geth reads them. For those
    parameter sets the outer PBKDF2-SHA256 layers run here and the
    memory-hard ROMix runs in native C (native/scrypt.c), differentially
    tested against hashlib on the parameters both accept."""
    import hashlib

    try:
        return scrypt(password, salt=salt, n=n, r=r, p=p, dklen=dklen,
                      maxmem=2**31 - 1)
    except ValueError:
        pass  # OpenSSL parameter bound: take the RFC 7914 composition
    from gethsharding_tpu import native

    blocks = hashlib.pbkdf2_hmac("sha256", password, salt, 1, p * 128 * r)
    mixed = native.scrypt_romix(blocks, p, n, r)
    if mixed is None:
        raise KeystoreError(
            "scrypt parameters unsupported by OpenSSL and the native "
            "ROMix is unavailable (GETHSHARDING_NO_NATIVE?)")
    return hashlib.pbkdf2_hmac("sha256", password, mixed, 1, dklen)


def _aes128_ctr(key16: bytes, iv16: bytes, data: bytes) -> bytes:
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    cipher = Cipher(algorithms.AES(key16), modes.CTR(iv16))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


def encrypt_key(priv: int, password: str, *, scrypt_n: int = STANDARD_SCRYPT_N,
                scrypt_p: int = STANDARD_SCRYPT_P) -> dict:
    """Private key -> Web3 Secret Storage v3 JSON object."""
    salt = secrets.token_bytes(32)
    derived = scrypt(password.encode(), salt=salt, n=scrypt_n, r=SCRYPT_R,
                     p=scrypt_p, dklen=SCRYPT_DKLEN, maxmem=2**31 - 1)
    iv = secrets.token_bytes(16)
    ciphertext = _aes128_ctr(derived[:16], iv, priv.to_bytes(32, "big"))
    mac = keccak256(derived[16:32] + ciphertext)
    address = secp256k1.priv_to_address(priv)
    return {
        "address": address.hex_str[2:],
        "crypto": {
            "cipher": "aes-128-ctr",
            "ciphertext": ciphertext.hex(),
            "cipherparams": {"iv": iv.hex()},
            "kdf": "scrypt",
            "kdfparams": {
                "dklen": SCRYPT_DKLEN,
                "n": scrypt_n,
                "p": scrypt_p,
                "r": SCRYPT_R,
                "salt": salt.hex(),
            },
            "mac": mac.hex(),
        },
        "id": "-".join(secrets.token_hex(n) for n in (4, 2, 2, 2, 6)),
        "version": 3,
    }


def decrypt_key(obj: dict, password: str) -> int:
    """Web3 Secret Storage JSON -> private key int. Raises KeystoreError on
    a wrong password (MAC mismatch) or unsupported parameters."""
    if obj.get("version") != 3:
        raise KeystoreError(f"unsupported keystore version {obj.get('version')}")
    crypto = obj["crypto"]
    if crypto.get("cipher") != "aes-128-ctr":
        raise KeystoreError(f"unsupported cipher {crypto.get('cipher')}")
    ciphertext = bytes.fromhex(crypto["ciphertext"])
    iv = bytes.fromhex(crypto["cipherparams"]["iv"])
    kdf = crypto.get("kdf")
    params = crypto["kdfparams"]
    if kdf == "scrypt":
        derived = scrypt_kdf(password.encode(),
                             salt=bytes.fromhex(params["salt"]),
                             n=params["n"], r=params["r"], p=params["p"],
                             dklen=params["dklen"])
    elif kdf == "pbkdf2":
        import hashlib

        if params.get("prf") != "hmac-sha256":
            raise KeystoreError(f"unsupported prf {params.get('prf')}")
        derived = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), bytes.fromhex(params["salt"]),
            params["c"], params["dklen"])
    else:
        raise KeystoreError(f"unsupported kdf {kdf}")
    mac = keccak256(derived[16:32] + ciphertext)
    if mac.hex() != crypto["mac"]:
        raise KeystoreError("could not decrypt key with given password")
    priv = int.from_bytes(_aes128_ctr(derived[:16], iv, ciphertext), "big")
    if not 1 <= priv < secp256k1.N:
        raise KeystoreError("decrypted key is out of range")
    return priv


@dataclass
class StoredAccount:
    address: Address20
    path: Path


class Keystore:
    """Directory of V3 key files (the `<datadir>/keystore` convention)."""

    def __init__(self, directory: os.PathLike | str, *,
                 scrypt_n: int = STANDARD_SCRYPT_N,
                 scrypt_p: int = STANDARD_SCRYPT_P):
        self.directory = Path(directory)
        self.scrypt_n = scrypt_n
        self.scrypt_p = scrypt_p

    def accounts(self) -> List[StoredAccount]:
        """Stored accounts, sorted by file name (creation order for files
        written by `store`, mirroring geth's URL ordering)."""
        out = []
        if not self.directory.is_dir():
            return out
        for path in sorted(self.directory.iterdir()):
            if not path.is_file():
                continue
            try:
                obj = json.loads(path.read_text())
                addr = Address20(bytes.fromhex(obj["address"]))
            except (ValueError, KeyError, json.JSONDecodeError):
                continue
            out.append(StoredAccount(address=addr, path=path))
        return out

    def store(self, priv: int, password: str) -> StoredAccount:
        """Encrypt and write a key file (UTC--<timestamp>--<address>)."""
        obj = encrypt_key(priv, password, scrypt_n=self.scrypt_n,
                          scrypt_p=self.scrypt_p)
        self.directory.mkdir(parents=True, exist_ok=True)
        stamp = time.strftime("%Y-%m-%dT%H-%M-%S", time.gmtime())
        path = self.directory / f"UTC--{stamp}--{obj['address']}"
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(obj, indent=2))
        os.replace(tmp, path)  # atomic: no torn key files on crash
        try:
            os.chmod(path, 0o600)
        except OSError:
            pass
        return StoredAccount(
            address=Address20(bytes.fromhex(obj["address"])), path=path)

    def unlock(self, address: Address20, password: str) -> int:
        """Decrypt the key file for `address`; KeystoreError if absent or
        the password is wrong."""
        for stored in self.accounts():
            if stored.address == address:
                return decrypt_key(json.loads(stored.path.read_text()),
                                   password)
        raise KeystoreError(f"no key file for {address.hex_str}")

    def load_or_create(self, password: str) -> int:
        """The node-identity flow: decrypt the first stored key, or create
        one if the keystore is empty. A restarted node keeps its address."""
        stored = self.accounts()
        if stored:
            return self.unlock(stored[0].address, password)
        priv = secrets.randbelow(secp256k1.N - 1) + 1
        self.store(priv, password)
        return priv
