"""SMCClient: the actor-side handle on the mainchain + SMC.

Parity: `sharding/mainchain/smc_client.go` (NewSMCClient :49, Start :72,
Sign :245, CreateTXOpts :112, WaitForTransaction :165) and `utils.go`
(dialRPC, initSMC). Differences by design: the default backend is the
in-process SimulatedMainchain (no IPC hop), and transactions apply
synchronously, so `wait_for_transaction` resolves immediately — the
polling contract is kept for the RPC backend.

Resilience (gethsharding_tpu/resilience):

- a real Stop: `stop()` marks the client stopped so in-flight
  `wait_for_transaction` polls exit promptly and every later call
  raises a clear `ClientStopped` instead of spinning against a dead
  backend;
- an optional `retry_policy` routes every idempotent backend READ
  through a `RetryExecutor` (seam ``mainchain``): transient connection
  errors against a flaky RPC chain process are absorbed with capped
  backoff and counted. Writes (votes, headers, registry transactions)
  are deliberately NOT retried — a connection error mid-write is
  ambiguous, and replaying it could double-submit. Env default:
  ``GETHSHARDING_CLIENT_RETRIES`` (attempts, 0 = off) +
  ``GETHSHARDING_CLIENT_RETRY_BASE_S``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from gethsharding_tpu.mainchain.accounts import Account, AccountManager
from gethsharding_tpu.params import Config, DEFAULT_CONFIG
from gethsharding_tpu.resilience.policy import RetryExecutor, RetryPolicy
from gethsharding_tpu.smc.chain import Receipt, SimulatedMainchain
from gethsharding_tpu.utils.hexbytes import Address20, Hash32


class ClientStopped(RuntimeError):
    """The SMCClient was stopped; this call can never complete."""


def _default_retry_policy() -> Optional[RetryPolicy]:
    attempts = int(os.environ.get("GETHSHARDING_CLIENT_RETRIES", "0"))
    if attempts <= 0:
        return None
    return RetryPolicy(
        attempts=attempts,
        base_s=float(os.environ.get(
            "GETHSHARDING_CLIENT_RETRY_BASE_S", "0.02")))


class SMCClient:
    """Wraps a chain backend + signing account into the actor-facing API.

    Exposes: Signer (sign/account), ChainReader (heads/blocks),
    ContractCaller and ContractTransactor (SMC surface) — the four role
    interfaces in `gethsharding_tpu.mainchain.interfaces`.
    """

    def __init__(self, backend: Optional[SimulatedMainchain] = None,
                 accounts: Optional[AccountManager] = None,
                 account: Optional[Account] = None,
                 deposit_flag: bool = False,
                 config: Config = DEFAULT_CONFIG,
                 retry_policy: Optional[RetryPolicy] = None):
        self.backend = backend if backend is not None else SimulatedMainchain(config)
        self.accounts = accounts or AccountManager()
        # a FRESH identity per client unless one is supplied (keystore or
        # caller): a fixed default seed would make every node in a
        # multi-node deployment the same notary
        self._account = account or self.accounts.new_account()
        self.deposit_flag = deposit_flag
        self.config = config
        self._stop = threading.Event()
        if retry_policy is None:
            retry_policy = _default_retry_policy()
        # the stop event doubles as the backoff sleeper: stop() wakes
        # an in-flight retry ladder mid-backoff, and the abort hook
        # turns it into ClientStopped instead of one more attempt
        # against a backend that is going away
        self._retry = (RetryExecutor("mainchain", retry_policy,
                                     sleep=self._stop.wait,
                                     abort=self._retry_abort)
                       if retry_policy is not None else None)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        # parity with SMCClient.Start: dial backend, unlock account, bind SMC
        self._stop.clear()
        self.accounts.unlock(self._account.address)

    def stop(self) -> None:
        """Mark the client stopped: in-flight `wait_for_transaction`
        polls exit promptly and later calls raise `ClientStopped`."""
        self._stop.set()

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def _ensure_running(self) -> None:
        if self._stop.is_set():
            raise ClientStopped("SMCClient is stopped")

    def _retry_abort(self) -> Optional[ClientStopped]:
        if self._stop.is_set():
            return ClientStopped("SMCClient is stopped")
        return None

    def _read(self, fn, *args, **kwargs):
        """One idempotent backend read: stop gate + retry executor."""
        self._ensure_running()
        if self._retry is None:
            return fn(*args, **kwargs)
        return self._retry.call(fn, *args, **kwargs)

    # -- Signer ------------------------------------------------------------

    def account(self) -> Address20:
        return self._account.address

    def sign(self, digest: bytes) -> bytes:
        self._ensure_running()
        return self.accounts.sign_hash(self._account.address, digest)

    def bls_sign(self, message: bytes):
        """Sign a vote message with the account's BLS vote key."""
        self._ensure_running()
        return self.accounts.bls_sign(self._account.address, message)

    # -- ChainReader -------------------------------------------------------

    def subscribe_new_head(self, callback):
        self._ensure_running()
        return self.backend.subscribe_new_head(callback)

    def block_by_number(self, number: Optional[int] = None):
        return self._read(self.backend.block_by_number, number)

    @property
    def block_number(self) -> int:
        return self._read(lambda: self.backend.block_number)

    def current_period(self) -> int:
        return self._read(self.backend.current_period)

    # -- ContractCaller ----------------------------------------------------

    def get_notary_in_committee(self, shard_id: int,
                                sender: Optional[Address20] = None) -> Address20:
        return self._read(
            self.backend.get_notary_in_committee,
            sender if sender is not None else self._account.address, shard_id)

    def committee_context(self) -> Optional[dict]:
        """One-call sampling context for local all-shard eligibility
        (None when the backend doesn't serve it)."""
        fn = getattr(self.backend, "committee_context", None)
        return self._read(fn) if fn is not None else None

    def notary_registry(self, address: Optional[Address20] = None):
        return self._read(
            self.backend.notary_registry,
            address if address is not None else self._account.address)

    def collation_record(self, shard_id: int, period: int):
        return self._read(self.backend.collation_record, shard_id, period)

    def last_submitted_collation(self, shard_id: int) -> int:
        return self._read(self.backend.last_submitted_collation, shard_id)

    def last_approved_collation(self, shard_id: int) -> int:
        return self._read(self.backend.last_approved_collation, shard_id)

    def has_voted(self, shard_id: int, index: int) -> bool:
        return self._read(self.backend.has_voted, shard_id, index)

    def get_vote_count(self, shard_id: int) -> int:
        return self._read(self.backend.get_vote_count, shard_id)

    def shard_count(self) -> int:
        return self._read(self.backend.shard_count)

    # -- ContractTransactor ------------------------------------------------
    # Writes get the stop gate but NO retry: replaying a write after an
    # ambiguous connection error could double-submit it.

    def register_notary(self) -> Receipt:
        self._ensure_running()
        # the vote pubkey + proof of possession register with the deposit;
        # validators batch-verify PoPs (rogue-key defense) in the audit
        return self.backend.register_notary(
            self._account.address,
            bls_pubkey=self._account.bls_pubkey,
            bls_pop=self.accounts.bls_proof_of_possession(
                self._account.address),
        )

    def deregister_notary(self) -> Receipt:
        self._ensure_running()
        return self.backend.deregister_notary(self._account.address)

    def release_notary(self) -> Receipt:
        self._ensure_running()
        return self.backend.release_notary(self._account.address)

    def add_header(self, shard_id: int, period: int, chunk_root: Hash32,
                   signature: bytes = b"") -> Receipt:
        self._ensure_running()
        return self.backend.add_header(self._account.address, shard_id,
                                       period, chunk_root, signature)

    def submit_vote(self, shard_id: int, period: int, index: int,
                    chunk_root: Hash32, bls_sig=None) -> Receipt:
        self._ensure_running()
        return self.backend.submit_vote(self._account.address, shard_id,
                                        period, index, chunk_root,
                                        bls_sig=bls_sig)

    def notary_by_pool_index(self, index: int) -> Optional[Address20]:
        return self._read(self.backend.notary_by_pool_index, index)

    def notary_registry_of(self, address: Address20):
        return self._read(self.backend.notary_registry, address)

    def verify_period_batch(self, period: int) -> Optional[bool]:
        """Chain-side batched vote-replay audit (None if unsupported)."""
        fn = getattr(self.backend, "verify_period_batch", None)
        return self._read(fn, period) if fn is not None else None

    def mirror_snapshot(self) -> dict:
        """One consistent snapshot of the hot-loop SMC read surface —
        a single round trip against backends that serve it in bulk
        (the RPC chain process), assembled locally otherwise."""
        fn = getattr(self.backend, "mirror_snapshot", None)
        if fn is not None:
            return self._read(fn)
        from gethsharding_tpu.mainchain.mirror import assemble_snapshot

        return assemble_snapshot(self)

    @property
    def reorg_generation(self) -> int:
        """Proxied so locally-assembled mirror snapshots carry the
        chain's rollback generation."""
        return getattr(self.backend, "reorg_generation", 0)

    def audit_data(self, period: int) -> dict:
        """Bulk period-audit data (records + vote sigs + voter pubkeys) —
        one round trip against backends that serve it in bulk; the
        in-process walk skips the hex wire codec (raw point tuples)."""
        fn = getattr(self.backend, "audit_data", None)
        if fn is not None:
            return self._read(fn, period)
        from gethsharding_tpu.mainchain.mirror import assemble_audit_data

        return assemble_audit_data(self, period, jsonable=False)

    # -- tx resilience (WaitForTransaction parity) ------------------------

    def wait_for_transaction(self, tx_hash: Hash32,
                             timeout_s: float = 10.0) -> Receipt:
        self._ensure_running()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            receipt = self._read(self.backend.transaction_receipt, tx_hash)
            if receipt is not None:
                return receipt
            # the stop event doubles as the poll sleep: a concurrent
            # stop() wakes the wait immediately instead of letting the
            # loop spin out its remaining timeout against a dead backend
            if self._stop.wait(0.01):
                raise ClientStopped(
                    f"client stopped while waiting for transaction "
                    f"{tx_hash.hex_str}")
        raise TimeoutError(f"transaction {tx_hash.hex_str} not mined in time")
