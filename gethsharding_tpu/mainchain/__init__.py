"""Mainchain bridge: how sharding actors reach the chain hosting the SMC.

Parity target: `sharding/mainchain/` — SMCClient (keystore signing, SMC
binding, tx waiting) and the narrow role interfaces
(`sharding/mainchain/interfaces.go:16-68`) that make actors testable
against fakes. The default backend is the in-process SimulatedMainchain;
the RPC bridge backend (separate mainchain process) plugs in behind the
same surface.
"""

from gethsharding_tpu.mainchain.interfaces import (  # noqa: F401
    ChainReader,
    ContractCaller,
    ContractTransactor,
    Signer,
)
from gethsharding_tpu.mainchain.client import SMCClient  # noqa: F401
from gethsharding_tpu.mainchain.accounts import AccountManager, Account  # noqa: F401
