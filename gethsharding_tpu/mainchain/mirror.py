"""Periodic SMC state mirror: the actor-side analog of chain sync.

The reference's downloader/fetcher stack (`eth/downloader`,
`eth/fetcher`) keeps a full node's local chain state current;
SURVEY.md §2.2 maps that role here to "a periodic SMC state mirror" —
actors don't import blocks, they track the one authoritative contract.
`StateMirror` maintains a per-head snapshot of the SMC surface an actor
reads in its hot loop (period, committee-sampling context, per-shard
submission/approval watermarks and current-period records) and persists
it in the shard DB, so:

- reads between heads hit the local snapshot instead of another RPC
  round trip (a remote actor's per-head chatter drops to ONE
  `mirror_snapshot`-shaped pull), and
- a restarted actor warm-starts from the last persisted snapshot
  before its first head arrives (checkpoint/resume §5.4: the SMC is
  the authoritative state; the mirror is the local cache of it).
"""

from __future__ import annotations

import json
import threading
import weakref
from typing import Dict, NamedTuple, Optional

from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.utils.hexbytes import Address20, Hash32

_DB_KEY = b"smc-mirror:latest"


class MirrorRecord(NamedTuple):
    """Decoded snapshot record — the read surface of an SMC collation
    record (chain.py CollationRecord duck-type) the notary hot loop
    consumes."""

    chunk_root: Hash32
    proposer: Address20
    vote_count: int
    is_elected: bool
    signature: bytes


def decode_record(rec: dict) -> MirrorRecord:
    return MirrorRecord(
        chunk_root=Hash32(bytes.fromhex(rec["chunk_root"])),
        proposer=Address20(bytes.fromhex(rec["proposer"])),
        vote_count=rec["vote_count"],
        is_elected=bool(rec["is_elected"]),
        signature=bytes.fromhex(rec.get("signature", "")),
    )


def decode_committee_context(ctx: Optional[dict]) -> Optional[dict]:
    """Inverse of `_ctx_jsonable` for the fields the sampling loop reads
    (blockhash + pool back to raw bytes)."""
    if ctx is None:
        return None
    out = dict(ctx)
    blockhash = out.get("blockhash")
    if isinstance(blockhash, str):
        out["blockhash"] = bytes.fromhex(blockhash)
    pool = out.get("pool")
    if pool is not None:
        out["pool"] = [bytes.fromhex(p) if isinstance(p, str) else p
                       for p in pool]
    return out


class StateMirror(Service):
    """Tracks SMC state per head; serves stale-bounded local reads."""

    name = "state-mirror"
    supervisable = True

    def __init__(self, client: SMCClient, shard_db=None):
        super().__init__()
        self.client = client
        self.db = shard_db
        self._lock = threading.Lock()
        self._snapshot: Optional[dict] = None
        self._gen = 0              # bumps with every stored snapshot
        self._persist_lock = threading.Lock()
        self._persisted_gen = 0
        self.refreshes = 0
        self._unsubscribe = None
        if self.db is not None:
            self._load_persisted()

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        self._unsubscribe = self.client.subscribe_new_head(self._on_head)
        try:
            self.refresh()  # don't wait for the first head
        except Exception as exc:
            self.record_error(f"initial mirror refresh failed: {exc}")

    def on_stop(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()

    def _on_head(self, block) -> None:
        try:
            self.refresh()
            self.record_success()
        except Exception as exc:
            self.record_failure(f"mirror refresh failed: {exc}")

    # -- the sync step -----------------------------------------------------

    def refresh(self) -> dict:
        """Pull one consistent snapshot of the hot-loop SMC surface —
        ONE bulk round trip when the backend serves `mirror_snapshot`
        (the RPC server does), the per-shard walk otherwise."""
        snapshot = self.client.mirror_snapshot()
        with self._lock:
            held = self._snapshot
            if held is not None:
                held_gen = held.get("reorg_gen", 0)
                new_gen = snapshot.get("reorg_gen", 0)
                # ordering is (reorg generation, block number): a stale
                # refresh from BEFORE a rollback must never overwrite the
                # post-reorg truth regardless of its higher block number,
                # and within one generation the head never regresses
                # (the head-callback vs on_start refresh race)
                if new_gen < held_gen or (
                        new_gen == held_gen
                        and (held["block_number"] or 0)
                        > (snapshot["block_number"] or 0)):
                    return held
            self._snapshot = snapshot
            self._gen += 1
            gen = self._gen
        self.refreshes += 1
        if self.db is not None:
            # persist OUTSIDE the read lock (disk I/O must not block
            # hot-loop snapshot() readers), but generation-checked so a
            # slower refresh that lost the in-memory race can never
            # overwrite a newer snapshot on disk
            payload = _encode(snapshot)
            try:
                with self._persist_lock:
                    if gen > self._persisted_gen:
                        self.db.put(_DB_KEY, payload)
                        self._persisted_gen = gen
            except Exception as exc:
                self.record_error(f"mirror persist failed: {exc}")
        return snapshot

    # -- reads -------------------------------------------------------------

    def snapshot(self) -> Optional[dict]:
        with self._lock:
            return self._snapshot

    def period(self) -> Optional[int]:
        snap = self.snapshot()
        return None if snap is None else snap["period"]

    def record(self, shard_id: int) -> Optional[dict]:
        """The current-period record mirror for a shard (None if absent)."""
        snap = self.snapshot()
        if snap is None:
            return None
        return snap["records"].get(shard_id)

    def record_view(self, shard_id: int) -> Optional[MirrorRecord]:
        """`record` decoded to the CollationRecord read surface."""
        rec = self.record(shard_id)
        return None if rec is None else decode_record(rec)

    @property
    def resumed_from_disk(self) -> bool:
        """True when the snapshot predates this process (warm start)."""
        return self._resumed

    # -- persistence -------------------------------------------------------

    _resumed = False

    def _load_persisted(self) -> None:
        try:
            raw = self.db.get(_DB_KEY)
        except Exception:
            return
        if not raw:
            return
        try:
            snapshot = _decode(raw)
        except (ValueError, KeyError):
            return  # a corrupt mirror is just a cold start
        with self._lock:
            self._snapshot = snapshot
        self._resumed = True


def assemble_snapshot(source) -> dict:
    """Build the mirror snapshot from anything with the client read
    surface (SMCClient, SimulatedMainchain, the RPC server's backend) —
    the ONE definition shared by the in-process walk and the bulk
    `shard_mirrorSnapshot` RPC method."""
    period = source.current_period()
    shard_count = source.shard_count()
    block_number = source.block_number
    if callable(block_number):  # pragma: no cover - surface variance
        block_number = block_number()
    submitted: Dict[int, int] = {}
    records: Dict[int, dict] = {}
    approved: Dict[int, int] = {}
    for shard_id in range(shard_count):
        last_sub = source.last_submitted_collation(shard_id)
        submitted[shard_id] = last_sub
        approved[shard_id] = source.last_approved_collation(shard_id)
        if last_sub == period:
            record = source.collation_record(shard_id, period)
            if record is not None:
                records[shard_id] = _rec_jsonable(record)
    # windback context: the last windback_depth CLOSED periods' records
    # (immutable once their period ends — votes only land in the current
    # period), so a remote notary's windback availability checks read
    # them from the snapshot instead of O(depth) collationRecord round
    # trips per vote (the r3 gap: actors/notary.py _check_windback).
    # Immutability also makes each closed period's walk cacheable: the
    # per-source memo avoids re-reading depth×shards records every head
    # (reorg_gen-keyed — a rollback can rewrite "closed" periods).
    depth = getattr(getattr(source, "config", None), "windback_depth", 0)
    reorg_gen = getattr(source, "reorg_generation", 0)
    prior: Dict[int, Dict[int, dict]] = {}
    # the cache is shared across the RPC server's handler threads and the
    # local mirror: all reads/writes/evictions happen under the lock (an
    # unlocked eviction racing an insert raises 'dict changed size during
    # iteration') — but the record WALK itself runs outside it, so a
    # slow source (remote client fallback) never serializes every other
    # snapshot assembly in the process behind one cold cache fill
    with _PRIOR_LOCK:
        cache = _PRIOR_CACHE.setdefault(source, {})
        have = {pp: cache.get((reorg_gen, pp))
                for pp in range(max(1, period - (depth or 0)), period)}
    for pp, cached in have.items():
        if cached is None:
            shard_recs: Dict[int, dict] = {}
            for shard_id in range(shard_count):
                record = source.collation_record(shard_id, pp)
                if record is not None:
                    shard_recs[shard_id] = _rec_jsonable(record)
            cached = shard_recs  # racing fills compute identical data
        prior[pp] = cached
    with _PRIOR_LOCK:
        for pp, recs in prior.items():
            cache[(reorg_gen, pp)] = recs
        for key in [k for k in cache
                    if k[0] != reorg_gen or k[1] < period - (depth or 0) - 2]:
            del cache[key]
    return {
        "block_number": block_number,
        "period": period,
        "shard_count": shard_count,
        # bumps on every chain rollback (smc/chain.py set_head): lets the
        # regression guard tell a reorg from a racing stale refresh
        "reorg_gen": getattr(source, "reorg_generation", 0),
        "committee_context": _ctx_jsonable(source.committee_context()),
        "last_submitted": submitted,
        "last_approved": approved,
        "records": records,
        "prior_records": prior,
    }


# per-source memo of closed-period record walks (see assemble_snapshot);
# weak keys so a dropped backend releases its cache
_PRIOR_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_PRIOR_LOCK = threading.Lock()


def _rec_jsonable(record) -> dict:
    return {
        "chunk_root": bytes(record.chunk_root).hex(),
        "proposer": bytes(record.proposer).hex(),
        "vote_count": record.vote_count,
        "is_elected": bool(record.is_elected),
        "signature": bytes(record.signature or b"").hex(),
    }


def assemble_audit_data(source, period: int, jsonable: bool = True) -> dict:
    """Bulk audit pull: for every shard with a collation record in
    `period`, the record's vote signatures AND the voters' registered
    BLS pubkeys (resolved by vote-time attribution) — ONE round trip
    for the remote notary's period audit instead of O(shards) record
    reads + O(votes) registry lookups. Shared by SMCClient's local walk
    and the `shard_auditData` RPC method.

    `jsonable=False` (the IN-PROCESS fast path) skips the hex wire
    codec entirely — sig/pubkey ride as raw point tuples, chunk_root as
    raw bytes, and the result carries `raw: True`. The codec round trip
    on 2×13,500 points was ~55% of the audit's host-side collection
    cost for a local notary paying it for nothing."""
    from gethsharding_tpu.rpc import codec

    shards: Dict[int, dict] = {}
    for shard_id in range(source.shard_count()):
        record = source.collation_record(shard_id, period)
        if record is None or not record.vote_sigs:
            continue
        votes = []
        for index, vote in record.vote_sigs.items():
            entry = source.notary_registry(vote.signer)
            pubkey = None if entry is None else entry.bls_pubkey
            if jsonable:
                votes.append({
                    "index": index,
                    "signer": bytes(vote.signer).hex(),
                    "sig": codec.enc_g1(vote.sig),
                    "pubkey": codec.enc_g2(pubkey),
                })
            else:
                votes.append({"index": index, "signer": vote.signer,
                              "sig": vote.sig, "pubkey": pubkey})
        shards[shard_id] = {
            "chunk_root": (bytes(record.chunk_root).hex() if jsonable
                           else bytes(record.chunk_root)),
            "vote_count": record.vote_count,
            "is_elected": bool(record.is_elected),
            "votes": votes,
        }
    out = {"period": period, "shards": shards}
    if not jsonable:
        out["raw"] = True
    return out


def _ctx_jsonable(ctx: Optional[dict]) -> Optional[dict]:
    if ctx is None:
        return None
    out = {}
    for key, val in ctx.items():
        if isinstance(val, (bytes, Hash32)):
            out[key] = bytes(val).hex()
        elif isinstance(val, (list, tuple)):
            out[key] = [bytes(v).hex() if isinstance(v, bytes) else v
                        for v in val]
        else:
            out[key] = val
    return out


def _encode(snapshot: dict) -> bytes:
    return json.dumps(snapshot, sort_keys=True).encode()


def restore_int_keys(snapshot: dict) -> dict:
    """JSON stringifies int dict keys; restore them in place."""
    for field in ("last_submitted", "last_approved", "records"):
        snapshot[field] = {int(k): v for k, v in snapshot[field].items()}
    prior = snapshot.get("prior_records")
    if prior is not None:
        snapshot["prior_records"] = {
            int(p): {int(s): rec for s, rec in shards.items()}
            for p, shards in prior.items()}
    return snapshot


def _decode(raw: bytes) -> dict:
    return restore_int_keys(json.loads(raw))
