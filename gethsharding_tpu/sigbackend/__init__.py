"""Signature backends: the `--sigbackend={python,jax}` seam.

The reference routes all signature work through native code chosen at
build time (cgo libsecp256k1, bn256 assembly — SURVEY.md §2.3). Here the
same seam is a runtime-selected backend object:

- ``python``: the scalar host implementations (`crypto/secp256k1`,
  `crypto/bn256`) — always available, no accelerator required. The
  byte-exact baseline.
- ``jax``: the batched TPU kernels (`ops/secp256k1_jax`,
  `ops/bn256_jax`) — batch-first; one dispatch verifies a whole period's
  worth of signatures. Imports JAX lazily so CPU-only control-plane
  processes never initialize an accelerator backend.

Both backends implement the same API and are differential-tested against
each other (tests/test_sigbackend.py). Actors take a backend instance;
the CLI exposes ``--sigbackend``.

- ``serving-python`` / ``serving-jax``: either backend behind the
  request-coalescing serving tier (``gethsharding_tpu/serving/``) —
  concurrent small calls from many threads share device dispatches;
  the CLI's ``--serving`` flag wires the same wrapper.
- ``failover-*``: any of the above as the PRIMARY behind a circuit
  breaker with the scalar ``python`` backend as the always-sound
  fallback (``gethsharding_tpu/resilience/breaker.py``): consecutive
  device faults or watchdog timeouts trip the breaker open, calls are
  served scalar while open, and a half-open differential spot-check
  re-promotes the accelerated path only when it agrees with the
  fallback byte-for-byte.
- the soundness spot-checker
  (``gethsharding_tpu/resilience/soundness.py``, ``--soundness-rate``)
  composes between them: a drop-in wrapper re-verifying a seeded-
  random row subset of a sampled fraction of dispatches against the
  scalar reference, so a device that silently returns WRONG verdicts
  (no exception to catch) still trips the breaker via
  `SoundnessViolation` within a quantifiable number of dispatches.

Package layout (the internal DAG is enforced by the layering lint rule
through ``analysis/layers.json``'s ``internal`` block):

- ``marshal.py`` — host->limb planes, padding policy, the u16 wire.
  Pure host arithmetic; the bottom of the package.
- ``layout.py`` — device placement: single device by default, the 1-D
  ``("shard",)`` mesh under ``--mesh-devices`` /
  ``GETHSHARDING_MESH_DEVICES`` > 1 (`NamedSharding(P('shard'))` over
  `parallel/mesh.make_mesh`).
- ``cache.py`` — the resident pk-plane LRU + batch memo; sharded
  per device on mesh layouts with per-device devscope owners.
- ``dispatch.py`` — `JaxSigBackend`: jit/pjit launch, DeviceTimer,
  compile_span, the wire ledger, and the one-collective mesh audit
  step. Lazily imported (PEP 562) so this package stays importable on
  accelerator-free control planes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.crypto import secp256k1 as ecdsa
# the padding policy lives in marshal.py; re-exported here because the
# serving layer (and tests) import it from the package root
from gethsharding_tpu.sigbackend.marshal import bucket_size
from gethsharding_tpu.utils.hexbytes import Address20


class VerdictFuture:
    """Handle on an in-flight committee verification.

    The jax backend's device dispatch is asynchronous: `result()` is
    where the verdict is pulled to the host (`np.asarray`), so a caller
    that submits period N+1 (or does any other host work) between
    submit and `result()` overlaps its host time with N's device
    execution. `concurrent.futures.Future`-compatible on the one method
    the notary uses (`result`), so the serving tier's real futures are
    drop-in."""

    __slots__ = ("_finalize", "_value", "_done")

    def __init__(self, finalize):
        self._finalize = finalize
        self._value = None
        self._done = False

    def result(self, timeout=None):
        if not self._done:
            self._value = self._finalize()
            self._done = True
            self._finalize = None  # drop the staged buffers
        return self._value

    def done(self) -> bool:
        return self._done


class SigBackend:
    """Batch signature operations used by the consensus hot loops."""

    name = "abstract"

    def ecrecover_addresses(self, digests: Sequence[bytes],
                            sigs65: Sequence[bytes]) -> List[Optional[Address20]]:
        """Recover the signer address per (32-byte digest, 65-byte [R||S||V])
        pair; None where the signature is invalid."""
        raise NotImplementedError

    def bls_verify_aggregates(
            self,
            messages: Sequence[bytes],
            agg_sigs: Sequence[bls.G1Point],
            agg_pks: Sequence[bls.G2Point]) -> List[bool]:
        """Verify one aggregate committee vote per message."""
        raise NotImplementedError

    def bls_verify_committees(
            self,
            messages: Sequence[bytes],
            sig_rows: Sequence[Sequence[bls.G1Point]],
            pk_rows: Sequence[Sequence[bls.G2Point]],
            pk_row_keys: Optional[Sequence] = None) -> List[bool]:
        """Aggregate each row's vote signatures + voter pubkeys and verify
        the aggregate against the row's message. The batch form of the
        whole committee check: with the jax backend both the aggregation
        (masked projective tree reduction) and the pairing run in ONE
        device dispatch. Empty rows are rejections (an empty committee
        proves nothing). `pk_row_keys` (optional, one hashable per row,
        e.g. the wire encoding) lets a backend cache the marshalled
        pubkey rows — keys MUST uniquely determine the row's points."""
        raise NotImplementedError

    def bls_verify_committees_async(
            self,
            messages: Sequence[bytes],
            sig_rows: Sequence[Sequence[bls.G1Point]],
            pk_rows: Sequence[Sequence[bls.G2Point]],
            pk_row_keys: Optional[Sequence] = None) -> VerdictFuture:
        """`bls_verify_committees` returning a verdict future instead of
        blocking on the host pull. The jax backend stages and launches
        the device dispatch before returning, so the caller marshals the
        NEXT batch while this one executes on device; scalar backends
        compute eagerly and return a resolved future (same contract, no
        overlap). Verdicts are bit-identical to the sync form."""
        out = self.bls_verify_committees(messages, sig_rows, pk_rows,
                                         pk_row_keys=pk_row_keys)
        future = VerdictFuture(lambda: out)
        future.result()  # scalar path: already computed; mark resolved
        return future

    def das_verify_samples(
            self,
            chunks: Sequence[bytes],
            indices: Sequence[int],
            proofs: Sequence[Sequence[bytes]],
            roots: Sequence[bytes]) -> List[bool]:
        """Verify one DAS sample per row: does `chunks[i]` sit at leaf
        `indices[i]` of the commitment tree rooted at `roots[i]`, per
        the sibling path `proofs[i]`? (das/proofs.py defines the leaf
        as the chunk's netstore address, so the per-row work is a full
        BMT recompute + path fold — keccak lanes.) Malformed rows
        (wrong chunk size, bad index, over-deep or ragged proofs) are
        False, never an exception: a hostile sample response must cost
        a verdict, not a batch. The jax backend runs the whole batch as
        ONE fixed-shape keccak dispatch over samples × shards."""
        raise NotImplementedError

    def das_verify_multiproofs(
            self,
            commitments: Sequence[bytes],
            index_rows: Sequence[Sequence[int]],
            eval_rows: Sequence[Sequence[int]],
            proofs: Sequence[bytes],
            ns: Sequence[int]) -> List[bool]:
        """Verify one DAS polynomial multiproof per row: does the
        64-byte G1 point `proofs[i]` open the 64-byte commitment
        `commitments[i]` to the claimed chunk-value evaluations
        `eval_rows[i]` at the sampled index set `index_rows[i]`, over
        a degree-<ns[i] evaluation domain? (das/pcs.py defines the
        scheme; one row = one sampled collation, the proof constant-
        size however many chunks the row samples.) Malformed rows (bad
        shapes, undecodable or off-curve points, duplicate or out-of-
        domain indices) are False, never an exception. The jax backend
        folds the whole batch into ONE two-pair pairing dispatch on
        the existing bn256 kernel."""
        raise NotImplementedError


class PythonSigBackend(SigBackend):
    """Scalar host crypto — parity baseline."""

    name = "python"

    def ecrecover_addresses(self, digests, sigs65):
        out: List[Optional[Address20]] = []
        for digest, sig in zip(digests, sigs65):
            try:
                signature = ecdsa.Signature.from_bytes65(bytes(sig))
                out.append(ecdsa.ecrecover_address(bytes(digest), signature))
            except (ValueError, AssertionError):
                out.append(None)
        return out

    def bls_verify_aggregates(self, messages, agg_sigs, agg_pks):
        return [
            bls.bls_verify(bytes(m), s, pk)
            for m, s, pk in zip(messages, agg_sigs, agg_pks)
        ]

    def bls_verify_committees(self, messages, sig_rows, pk_rows,
                              pk_row_keys=None):
        return [
            bls.bls_verify_aggregate(
                bytes(m), bls.bls_aggregate_sigs(sigs), list(pks))
            for m, sigs, pks in zip(messages, sig_rows, pk_rows)
        ]

    def das_verify_samples(self, chunks, indices, proofs, roots):
        # lazy import: the das package is optional workload surface,
        # not a dependency of every scalar control plane
        from gethsharding_tpu.das.proofs import verify_samples

        return verify_samples(chunks, indices, proofs, roots)

    def das_verify_multiproofs(self, commitments, index_rows, eval_rows,
                               proofs, ns):
        # lazy for the same reason as das_verify_samples
        from gethsharding_tpu.das.poly_proofs import verify_multiproofs

        return verify_multiproofs(commitments, index_rows, eval_rows,
                                  proofs, ns)


def _jax_factory() -> SigBackend:
    """Factory for the accelerated backend. Lazy import of dispatch.py
    (which eagerly imports layout/cache/marshal): requesting 'jax' is
    the moment a process opts into the accelerator plane."""
    from gethsharding_tpu.sigbackend.dispatch import JaxSigBackend

    return JaxSigBackend()


def _serving_factory(inner_name: str):
    """Factory for the serving-tier wrappers ('serving-python' /
    'serving-jax'): the wrapped backend stays the process singleton, the
    wrapper adds the micro-batching admission tier in front of it. Lazy
    import: control planes that never serve must not pay for the
    serving threads module."""
    def build() -> SigBackend:
        from gethsharding_tpu.serving.backend import ServingSigBackend

        return ServingSigBackend(get_backend(inner_name))

    return build


def _failover_factory(primary_name: str):
    """Factory for the breaker-guarded wrappers ('failover-<primary>'):
    the primary stays the registry singleton; the scalar python backend
    is the always-available fallback. Lazy import: only nodes that opt
    into failover load the resilience layer."""
    def build() -> SigBackend:
        from gethsharding_tpu.resilience.breaker import FailoverSigBackend

        return FailoverSigBackend(get_backend(primary_name),
                                  get_backend("python"))

    return build


_BACKENDS = {
    "python": PythonSigBackend,
    "jax": _jax_factory,
    "serving-python": _serving_factory("python"),
    "serving-jax": _serving_factory("jax"),
    "failover-python": _failover_factory("python"),
    "failover-jax": _failover_factory("jax"),
    "failover-serving-python": _failover_factory("serving-python"),
    "failover-serving-jax": _failover_factory("serving-jax"),
}
_cache: dict = {}


def get_backend(name: str = "python") -> SigBackend:
    """Backend registry: 'python' (scalar host), 'jax' (batched TPU),
    the 'serving-*' coalescing wrappers, or the 'failover-*'
    breaker-guarded wrappers over any of them."""
    if name not in _BACKENDS:
        raise ValueError(
            f"unknown sigbackend {name!r}; choose from {sorted(_BACKENDS)}")
    if name not in _cache:
        _cache[name] = _BACKENDS[name]()
    return _cache[name]


def __getattr__(name: str):
    # PEP 562: `from gethsharding_tpu.sigbackend import JaxSigBackend`
    # keeps working without this package eagerly importing dispatch.py
    # (and through it the kernels) on accelerator-free control planes
    if name == "JaxSigBackend":
        from gethsharding_tpu.sigbackend.dispatch import JaxSigBackend

        return JaxSigBackend
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
