"""Host->limb marshalling: the padding policy and the u16 wire.

The host half of every jax dispatch lives here — pure functions from
protocol objects (messages, signature rows, pubkey rows) to the padded
limb planes the kernels consume. Nothing in this module touches a
device: marshalling must stay overlappable with the PREVIOUS batch's
device execution (the async committee path), so it is host arithmetic
by construction.

Layering (enforced by the `layering` shardlint rule through
``layers.json``'s ``internal`` DAG for this package): ``marshal`` is
the bottom of the ``sigbackend`` package — ``layout``, ``cache`` and
``dispatch`` all build on it, it imports none of them.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from gethsharding_tpu.crypto import bn256 as bls

# canonical limb bound: the host marshallers emit 12-bit limbs, so the
# u16 wire narrowing is value-preserving iff every limb is below this
U16_LIMB_BOUND = 1 << 12


def bucket_size(n: int) -> int:
    """THE batch padding policy: quarter-power-of-two buckets (…, 64,
    80, 96, 112, 128, …) — a handful of compiled shapes per octave
    instead of one per distinct batch size, with <19% padded rows above
    8 (worst case 65 -> 80); the plain pow2 rule wasted 28% of every
    kernel launch at the production 100-shard audit (100 -> 128).

    Public and single-sourced on purpose: the serving layer sizes its
    coalesced flush quanta with the SAME function the jax backend pads
    with, so coalesced traffic lands on shapes the device has already
    compiled instead of widening the compile cache."""
    if n <= 8:  # pow2 below 8: tiny pads, few compiled shapes
        size = 1
        while size < n:
            size *= 2
        return size
    size = 8
    while size * 2 < n:
        size *= 2
    # quarter steps inside the octave (size, 2*size]
    quarter = size // 4
    return -(-n // quarter) * quarter


def committee_width(sig_rows: Sequence[Sequence],
                    pk_rows: Sequence[Sequence]) -> int:
    """The committee-axis padding policy. The tree reduction takes any
    width (binary segment decomposition), so bucket only enough to
    bound the number of compiled shapes — next multiple of 16
    (135 -> 144; the old mult-32 rule padded 18% of the committee
    work), power-of-two-ish below 32."""
    width = max([1] + [len(r) for r in sig_rows]
                + [len(r) for r in pk_rows])
    return bucket_size(width) if width <= 32 else -(-width // 16) * 16


def wire_dtype(wire_u16: bool, check: bool):
    """The dtype host marshallers emit the wire planes in. Under the
    u16 wire the planes are assembled AS uint16 (no second full-plane
    narrowing copy); GETHSHARDING_CHECK=1 keeps them int32 so the
    narrowing site can pin the canonical-limb invariant."""
    import numpy as np

    return np.uint16 if wire_u16 and not check else np.int32


def narrow_u16(a, check: bool):
    """Narrow a limb plane to the uint16 wire. u16 wire invariant:
    every wire plane holds CANONICAL 12-bit limbs (the host marshallers
    emit [0, 2^12)), so narrowing is value-preserving. A lazy/wide-form
    limb would wrap silently and corrupt the verdict —
    GETHSHARDING_CHECK=1 pins the invariant here; without it the
    marshallers emit the wire width directly (no second copy)."""
    import numpy as np

    arr = np.asarray(a)
    if check and arr.size:
        # bound is the CANONICAL limb width (12-bit), not the wire
        # width: a wide-form limb in [2^12, 2^16) would survive the
        # cast but violate the kernel's headroom
        assert arr.min() >= 0 and arr.max() < U16_LIMB_BOUND, (
            "u16 wire requires canonical limbs in [0, 2^12)")
    # copy=False: planes marshalled straight into uint16 (and
    # cache-held rows) are not re-copied per dispatch
    return arr.astype(np.uint16, copy=False)


def wire_converter(wire_u16: bool, check: bool):
    """The per-plane host conversion for one dispatch: `narrow_u16`
    under the u16 wire, plain `np.asarray` otherwise."""
    import numpy as np

    if wire_u16:
        return lambda a: narrow_u16(a, check)
    return np.asarray


def assert_canonical_limbs(*planes) -> None:
    """The u16 invariant, pinned once per row AT SHIP TIME for planes
    that travel through the resident cache (hit rows were checked when
    first transferred)."""
    for plane in planes:
        assert int(plane.min()) >= 0 \
            and int(plane.max()) < U16_LIMB_BOUND, (
            "u16 wire requires canonical limbs in [0, 2^12)")


def committee_host_planes(bn, messages: Sequence[bytes],
                          sig_rows: Sequence[Sequence],
                          pad: int, width: int, out_dtype) -> dict:
    """The fresh-per-period host planes of a committee dispatch: message
    hashes and the signature planes + masks, padded to the bucket.
    ``bn`` is the caller's kernel module (`ops/bn256_jax`) — passed in
    so this module never imports the ops package eagerly."""
    hashes = [bls.hash_to_g1(bytes(m)) for m in messages] + [None] * pad
    hx, hy, hok = bn.g1_to_limbs(hashes)
    sx, sy, sm = bn.g1_committee_to_limbs(
        list(sig_rows) + [[]] * pad, width, out_dtype=out_dtype)
    return {"hx": hx, "hy": hy, "hok": hok, "sx": sx, "sy": sy, "sm": sm}


def normalize_row_keys(pk_row_keys,
                       n_rows: int) -> Optional[List]:
    """Normalize to EXACTLY one key per (padded) row: a short caller
    list means trailing rows are uncached (None), a surplus is
    dropped — the host row cache's contract."""
    if pk_row_keys is None:
        return None
    keys = list(pk_row_keys)[:n_rows]
    keys += [None] * (n_rows - len(keys))
    return keys
