"""Device dispatch: jit/pjit launch, DeviceTimer, wire ledger.

`JaxSigBackend` — the batched accelerator backend — composes the other
three submodules: `marshal` builds the host limb planes, `layout`
decides where they land (single device, or the 1-D shard mesh), and
`cache` (mixed in) keeps the recurring pk planes device-resident.
This module owns what remains: the jitted kernels, the compile-cache
bookkeeping (`_note_shape` + `compile_span`), the `DeviceTimer`
attribution of every dispatch, and the per-dispatch wire ledger.

The mesh committee path (`_committee_submit_mesh`) is the tentpole:
the whole period audit runs as ONE pjit'd step — a `shard_map` whose
only cross-device traffic is the vote-total `psum` (asserted per
compiled executable via `layout.count_collectives` over the AOT HLO).
Everything else — verdict plane, pk planes, fresh-per-period planes —
stays strictly device-local under `NamedSharding(P('shard'))`.

Never import this module eagerly: `sigbackend/__init__` exposes
`JaxSigBackend` lazily (PEP 562) so CPU-only control planes never
initialize an accelerator backend.
"""

from __future__ import annotations

import os

from gethsharding_tpu import metrics, tracing
from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.crypto import secp256k1 as ecdsa
# DeviceTimer is THE timing primitive of every dispatch path below: it
# forces a real device->host pull (block_until_ready can silently no-op
# under the tunnel plugin — the r4 hazard), self-checks block-vs-pull
# divergence into `perfwatch/timer_suspect`, and feeds the
# sig/{marshal_time,device_time} rollups; RECORDER keeps the last-N
# dispatch wire ledgers for the flight recorder's post-mortem bundles
from gethsharding_tpu.perfwatch import RECORDER, DeviceTimer
from gethsharding_tpu.sigbackend import SigBackend, VerdictFuture
from gethsharding_tpu.sigbackend import layout as layout_mod
from gethsharding_tpu.sigbackend import marshal
from gethsharding_tpu.sigbackend.cache import ResidentPkCache
from gethsharding_tpu.sigbackend.marshal import bucket_size


class JaxSigBackend(ResidentPkCache, SigBackend):
    """Batched accelerator kernels; one dispatch per batch."""

    name = "jax"

    def __init__(self, mesh_devices=None):
        import jax  # lazy: only sig-verifying processes touch the backend
        import jax.numpy as jnp

        from gethsharding_tpu.ops import bn256_jax, secp256k1_jax

        self._jax = jax
        self._jnp = jnp
        self._bn = bn256_jax
        self._sec = secp256k1_jax
        self._recover = jax.jit(secp256k1_jax.ecrecover_batch)
        self._bls = jax.jit(bn256_jax.bls_verify_aggregate_batch)
        self._bls_committee = jax.jit(
            bn256_jax.bls_aggregate_verify_committee_batch)
        # GETHSHARDING_TPU_WIRE=u16: ship limb planes over the
        # host->device link as uint16 (12-bit limbs waste 20 of 32 bits;
        # halves the audit's transfer bytes over the tunnel) and widen
        # to int32 ON DEVICE before the kernel — value-identical, the
        # wire format never reaches the arithmetic
        self._wire_u16 = os.environ.get("GETHSHARDING_TPU_WIRE") == "u16"
        self._wire = "u16" if self._wire_u16 else "i32"

        def _committee_u16(hx, hy, sx, sy, sm, px, py, pm, hok):
            i32 = jnp.int32
            return bn256_jax.bls_aggregate_verify_committee_batch(
                hx.astype(i32), hy.astype(i32), sx.astype(i32),
                sy.astype(i32), sm, px.astype(i32), py.astype(i32),
                pm, hok)

        self._bls_committee_u16 = jax.jit(_committee_u16)
        # GETHSHARDING_PRECOMP: fixed-base pairing precomputation
        # (default on). The committee path consumes device-resident
        # Miller line tables keyed by pk_row_key instead of re-running
        # the fixed-argument point arithmetic every dispatch — a cold
        # row pays one precompute dispatch, every warm audit ships zero
        # G2 bytes AND skips the point-arithmetic half of the Miller
        # loop. 0 restores today's recompute path.
        precomp = os.environ.get("GETHSHARDING_PRECOMP", "1")
        if precomp not in ("0", "1"):
            raise ValueError(
                f"GETHSHARDING_PRECOMP={precomp!r}: want 0 or 1")
        self._precomp = precomp == "1"
        # GETHSHARDING_PRECOMP_BLOCKS: split the precomp dispatch into
        # N lane blocks, enqueuing block k+1's Miller stage BEFORE
        # block k's finalexp so the device overlaps sparse line
        # evaluation with the previous block's finalexp mega-kernel.
        # 1 = single fused dispatch (no pipelining).
        blocks = os.environ.get("GETHSHARDING_PRECOMP_BLOCKS", "2")
        try:
            self._precomp_blocks = int(blocks)
        except ValueError:
            self._precomp_blocks = 0
        if self._precomp_blocks < 1:
            raise ValueError(
                f"GETHSHARDING_PRECOMP_BLOCKS={blocks!r}: want a"
                " positive integer")

        def _precompute_planes(px, py, pm):
            i32 = jnp.int32
            return bn256_jax.precompute_g2_lines(
                px.astype(i32), py.astype(i32), pm)

        # one precompute jit serves every layout: committed inputs keep
        # the dispatch on the owning device (mesh shards included); the
        # astype is a no-op on the i32 wire
        self._precompute = jax.jit(_precompute_planes)

        def _precomp_full(hx, hy, sx, sy, sm, tab, inf, hok, gen):
            i32 = jnp.int32
            return bn256_jax.bls_verify_committee_precomp_batch(
                hx.astype(i32), hy.astype(i32), sx.astype(i32),
                sy.astype(i32), sm, tab, inf, hok, gen_lines=gen)

        def _precomp_miller(hx, hy, sx, sy, sm, tab, inf, hok, gen):
            i32 = jnp.int32
            return bn256_jax.bls_committee_precomp_miller(
                hx.astype(i32), hy.astype(i32), sx.astype(i32),
                sy.astype(i32), sm, tab, inf, hok, gen_lines=gen)

        self._precomp_full = jax.jit(_precomp_full)
        self._precomp_miller = jax.jit(_precomp_miller)
        self._precomp_finalexp = jax.jit(
            bn256_jax.bls_committee_precomp_finalexp)
        # the backend is a process-wide singleton shared by every actor
        # thread (get_backend caches instances): all cache structures
        # are lock-guarded (cache.py)
        self._init_pk_caches()
        self._m_wire_bytes = metrics.counter("jax/wire/bytes")
        self._m_pk_hit_bytes = metrics.counter("jax/wire/pk_device_hit_bytes")
        # device-time attribution rollups (sig/{marshal_time,
        # device_time}) are fed by the perfwatch DeviceTimer each
        # dispatch path below constructs — one timing scheme, with the
        # block-vs-pull self-check built in
        # compile-cache visibility: jax.jit compiles once per argument
        # SHAPE, and every padded bucket this process has not dispatched
        # before is a fresh XLA compile (seconds to minutes). Tracking
        # (op, bucket-shape) first-sightings makes recompile storms —
        # e.g. unbucketed traffic widening the shape set — visible as
        # counters and span tags instead of mystery latency spikes.
        import threading

        self._shape_seen: set = set()
        self._shape_lock = threading.Lock()
        self._m_shape_hit = metrics.counter("jax/compile_cache/hits")
        self._m_shape_miss = metrics.counter("jax/compile_cache/misses")
        from gethsharding_tpu import devscope

        self._compiles = devscope.COMPILES
        # THE layout decision: single-device unless the constructor or
        # GETHSHARDING_MESH_DEVICES asks for a mesh. Everything below
        # branches on `self._layout.is_mesh`, nothing else.
        self._layout = layout_mod.DeviceLayout(
            layout_mod.mesh_devices_requested(mesh_devices))
        if self._layout.is_mesh:
            # per-device cache shards + their devscope census owners
            self._init_mesh_shards(self._layout)
            # AOT executables per (bucket, width, wire) — lowering once
            # through .lower().compile() yields BOTH the executable and
            # its HLO text, so the one-collective transfer-ledger check
            # costs no second compilation
            self._mesh_exec: dict = {}
            self._mesh_collectives: dict = {}
            shard_map = layout_mod.get_shard_map()
            from jax.sharding import PartitionSpec

            mesh = self._layout.mesh
            spec = self._layout.shard_spec()
            axis_names = mesh.axis_names

            def _mesh_step(hx, hy, sx, sy, sm, px, py, pm, hok):
                # the ONE pjit'd audit step: each device verifies its
                # slab of committees (astype is a no-op on the i32
                # wire), then the vote total — the ONLY cross-device
                # value — is psum'd. Everything else stays local.
                i32 = jnp.int32
                ok = bn256_jax.bls_aggregate_verify_committee_batch(
                    hx.astype(i32), hy.astype(i32), sx.astype(i32),
                    sy.astype(i32), sm, px.astype(i32),
                    py.astype(i32), pm, hok)
                votes = jax.lax.psum(jnp.sum(ok.astype(i32)), axis_names)
                return ok, votes

            self._bls_committee_mesh = jax.jit(shard_map(
                _mesh_step, mesh=mesh, in_specs=(spec,) * 9,
                out_specs=(spec, PartitionSpec())))

            def _mesh_step_precomp(hx, hy, sx, sy, sm, tab, inf, hok,
                                   gen):
                # the precomp twin of the ONE pjit'd audit step: line
                # tables arrive pre-sharded from the per-device cache
                # shards, the replicated generator table rides along,
                # and the vote-total psum stays the only collective
                i32 = jnp.int32
                ok = bn256_jax.bls_verify_committee_precomp_batch(
                    hx.astype(i32), hy.astype(i32), sx.astype(i32),
                    sy.astype(i32), sm, tab, inf, hok, gen_lines=gen)
                votes = jax.lax.psum(jnp.sum(ok.astype(i32)), axis_names)
                return ok, votes

            self._bls_committee_mesh_precomp = jax.jit(shard_map(
                _mesh_step_precomp, mesh=mesh,
                in_specs=(spec,) * 8 + (PartitionSpec(),),
                out_specs=(spec, PartitionSpec())))
        # the G2-generator line table: precomputed at import (host),
        # shipped ONCE at construction and passed into every precomp
        # executable as an argument — an embedded constant would
        # re-materialize per compiled shape. Censused by the resident
        # owners (cache.py) so devscope attribution stays drift-free.
        if self._precomp:
            if self._layout.is_mesh:
                from jax.sharding import NamedSharding, PartitionSpec

                self._gen_lines_mesh = jax.device_put(
                    bn256_jax.generator_line_table(),
                    NamedSharding(self._layout.mesh, PartitionSpec()))
            else:
                self._gen_lines_dev = jnp.asarray(
                    bn256_jax.generator_line_table())
        # device-memory attribution: the resident pk-plane LRU (and on
        # mesh layouts each per-device shard) registers as a devscope
        # census owner — cache.py holds the weakref plumbing
        self._register_census_owner()

    def _note_shape(self, op: str, *shape) -> bool:
        """Count a dispatch against the per-shape compile cache; True
        when this (op, shape) is NEW to the process (an XLA compile).
        Fresh sightings also feed the devscope recompile-storm window
        (compilewatch.py) — hits cost one extra early-returning call."""
        key = (op,) + shape
        with self._shape_lock:
            fresh = key not in self._shape_seen
            if fresh:
                self._shape_seen.add(key)
        (self._m_shape_miss if fresh else self._m_shape_hit).inc()
        compiles = getattr(self, "_compiles", None)
        if compiles is None:
            # partially-built instances (tests stub the tracking state
            # via __new__) self-heal onto the process watch; idempotent
            from gethsharding_tpu import devscope

            compiles = self._compiles = devscope.COMPILES
        compiles.saw(op, shape, fresh)
        return fresh

    # the module-level bucket_size, kept as a staticmethod so kernel
    # call sites read as "this backend's padding policy"
    _bucket = staticmethod(bucket_size)

    # the device-resident G2-generator line table (single-device /
    # mesh-replicated) — None when GETHSHARDING_PRECOMP=0 or on
    # partially-built test instances
    _gen_lines_dev = None
    _gen_lines_mesh = None

    def _precomp_nblocks(self, bucket: int) -> int:
        """Pipeline block count for a precomp dispatch: the largest
        divisor of `bucket` not above GETHSHARDING_PRECOMP_BLOCKS, and
        never splitting below the finalexp mega-kernel's lane block
        (a partial block would pad back to BLOCK_LANES, wasting
        lanes)."""
        nb = min(self._precomp_blocks, bucket)
        if nb > 1 and self._bn.FINALEXP == "mega":
            from gethsharding_tpu.ops.pallas_finalexp import block_lanes

            nb = min(nb, max(1, bucket // block_lanes()))
        while nb > 1 and bucket % nb:
            nb -= 1
        return nb

    def _precomp_launch(self, args, bucket: int, blocks: int):
        """Launch the precomp committee dispatch: one fused kernel, or
        `blocks` pipelined lane blocks. Block k+1's Miller stage is
        enqueued BEFORE block k's finalexp, so the device overlaps the
        sparse line evaluations with the previous block's finalexp
        mega-kernel (every launch is async; the caller's pull is the
        only barrier). Splitting is along the independent row axis —
        per-row values, and therefore verdicts, are identical to the
        fused launch."""
        jnp = self._jnp
        gen = self._gen_lines_dev
        if blocks <= 1:
            return self._precomp_full(*args, gen)
        bs = bucket // blocks
        staged = None
        outs = []
        for k in range(blocks):
            blk = tuple(a[k * bs:(k + 1) * bs] for a in args)
            nxt = self._precomp_miller(*blk, gen)
            if staged is not None:
                outs.append(self._precomp_finalexp(*staged))
            staged = nxt
        outs.append(self._precomp_finalexp(*staged))
        return jnp.concatenate(outs)

    def ecrecover_addresses(self, digests, sigs65):
        import numpy as np

        jnp = self._jnp
        n = len(digests)
        if n == 0:
            return []
        dt = DeviceTimer("ecrecover")
        sigs, valid, host_rows = [], [], []
        for i, sig in enumerate(sigs65):
            sig = bytes(sig)
            if len(sig) == 65 and sig[64] in (0, 1):
                sigs.append(ecdsa.Signature.from_bytes65(sig))
                valid.append(True)
            else:
                if len(sig) == 65 and sig[64] in (2, 3):
                    # rare r+n overflow recids: scalar host fallback keeps
                    # exact RecoverPubkey parity
                    host_rows.append(i)
                sigs.append(ecdsa.Signature(r=1, s=1, v=0))  # placeholder
                valid.append(False)
        bucket = self._bucket(n)
        fresh = self._note_shape("ecrecover", bucket)
        pad = bucket - n
        sigs.extend([ecdsa.Signature(r=1, s=1, v=0)] * pad)
        valid.extend([False] * pad)
        e = self._sec.hashes_to_limbs(
            [bytes(d) for d in digests] + [b"\x00" * 32] * pad)
        r, s, v = self._sec.sigs_to_limbs(sigs)
        tracer = tracing.TRACER
        dt.dispatched()
        # compile_span: a fresh shape's launch wall (trace + XLA compile
        # + enqueue) lands in the devscope compile ledger; on hits this
        # is one branch
        with self._compiles.compile_span("ecrecover", (bucket,), fresh):
            qx, qy, ok = self._recover(
                jnp.asarray(e), jnp.asarray(r), jnp.asarray(s),
                jnp.asarray(v), jnp.asarray(np.asarray(valid)))
        # the checked pull on `ok` is the dispatch barrier (block-vs-pull
        # self-checked); limbs_to_pubkeys then pulls the sibling buffers
        # of the SAME computation, so the device phase closes only after
        # the dispatch has actually executed and materialized. The host
        # `ok` is passed through — pulling it twice would add a second
        # device->host round trip per dispatch.
        ok_host = dt.pull(ok)
        pubs = self._sec.limbs_to_pubkeys(qx, qy, ok_host)[:n]
        dt.done()
        if tracer.enabled:
            tracer.record("jax/ecrecover_dispatch", dt.t_dispatch, dt.t_done,
                          tags={"rows": n, "bucket": bucket,
                                "compile": "miss" if fresh else "hit",
                                "suspect": dt.suspect,
                                "marshal_ms": round(dt.marshal_s * 1e3, 3),
                                "device_ms": round(dt.device_s * 1e3, 3)})
        out = [ecdsa.pubkey_to_address(p) if p is not None else None
               for p in pubs]
        for i in host_rows:
            try:
                out[i] = ecdsa.ecrecover_address(
                    bytes(digests[i]),
                    ecdsa.Signature.from_bytes65(bytes(sigs65[i])))
            except (ValueError, AssertionError):
                out[i] = None
        return out

    def bls_verify_aggregates(self, messages, agg_sigs, agg_pks):
        jnp = self._jnp
        n = len(messages)
        if n == 0:
            return []
        dt = DeviceTimer("bls_aggregate")
        bucket = self._bucket(n)
        fresh = self._note_shape("bls_aggregate", bucket)
        pad = bucket - n
        hashes = [bls.hash_to_g1(bytes(m)) for m in messages] + [None] * pad
        hx, hy, hok = self._bn.g1_to_limbs(hashes)
        sx, sy, sok = self._bn.g1_to_limbs(list(agg_sigs) + [None] * pad)
        pkx, pky, pok = self._bn.g2_to_limbs(list(agg_pks) + [None] * pad)
        # infinity signature/key is an outright rejection (scalar parity)
        valid = hok & sok & pok
        tracer = tracing.TRACER
        dt.dispatched()
        with self._compiles.compile_span("bls_aggregate", (bucket,), fresh):
            out = self._bls(
                jnp.asarray(hx), jnp.asarray(hy), jnp.asarray(sx),
                jnp.asarray(sy), jnp.asarray(pkx), jnp.asarray(pky),
                jnp.asarray(valid))
        res = [bool(b) for b in dt.pull(out)[:n]]
        dt.done()
        if tracer.enabled:
            tracer.record("jax/bls_aggregate_dispatch", dt.t_dispatch,
                          dt.t_done,
                          tags={"rows": n, "bucket": bucket,
                                "compile": "miss" if fresh else "hit",
                                "suspect": dt.suspect,
                                "marshal_ms": round(dt.marshal_s * 1e3, 3),
                                "device_ms": round(dt.device_s * 1e3, 3)})
        return res

    def bls_verify_committees(self, messages, sig_rows, pk_rows,
                              pk_row_keys=None):
        return self._committee_submit(messages, sig_rows, pk_rows,
                                      pk_row_keys).result()

    def bls_verify_committees_async(self, messages, sig_rows, pk_rows,
                                    pk_row_keys=None):
        """Stage + launch the dispatch NOW; the device executes while
        the caller marshals the next period. `result()` is the host
        pull."""
        return self._committee_submit(messages, sig_rows, pk_rows,
                                      pk_row_keys)

    def das_verify_samples(self, chunks, indices, proofs, roots):
        """One batched keccak dispatch for the whole sample batch: BMT
        recompute of every chunk (128 leaf lanes + 7 pair levels) +
        path fold, `vmap`-shaped over samples × shards. Verdicts are
        bit-identical to the scalar reference because every malformed-
        row rejection is folded into the `valid` plane at marshal time
        (das/proofs.marshal_samples)."""
        from gethsharding_tpu.das import proofs as das_proofs

        jnp = self._jnp
        n = len(chunks)
        if n == 0:
            self.last_wire = None
            return []
        dt = DeviceTimer("das_verify")
        bucket = self._bucket(n)
        fresh = self._note_shape("das_verify", bucket)
        st = das_proofs.marshal_samples(chunks, indices, proofs, roots,
                                        bucket)
        planes = (st["chunks"], st["sibs"], st["bits"], st["levels"],
                  st["roots"], st["valid"])
        sample_bytes = sum(int(p.nbytes) for p in planes)
        # the per-dispatch wire ledger (same contract as the committee
        # path: pure nbytes arithmetic, no device sync) — the sample
        # planes ARE this dispatch's host->device bytes
        self.last_wire = {"op": "das_verify_samples",
                          "wire_bytes": sample_bytes,
                          "sample_wire_bytes": sample_bytes,
                          "rows": n, "bucket": bucket, "wire": self._wire}
        RECORDER.record_wire("das_verify_samples", self.last_wire)
        self._m_wire_bytes.inc(sample_bytes)
        tracing.tag_current_add(wire_bytes=sample_bytes,
                                sample_wire_bytes=sample_bytes)
        tracer = tracing.TRACER
        dt.dispatched()
        with self._compiles.compile_span("das_verify", (bucket,), fresh):
            out = das_proofs.batch_verifier()(
                *(jnp.asarray(p) for p in planes))
        res = [bool(b) for b in dt.pull(out)[:n]]
        dt.done()
        if tracer.enabled:
            tracer.record("jax/das_verify_dispatch", dt.t_dispatch,
                          dt.t_done,
                          tags={"rows": n, "bucket": bucket,
                                "compile": "miss" if fresh else "hit",
                                "sample_wire_bytes": sample_bytes,
                                "suspect": dt.suspect,
                                "marshal_ms": round(dt.marshal_s * 1e3, 3),
                                "device_ms": round(dt.device_s * 1e3, 3)})
        return res

    def das_verify_multiproofs(self, commitments, index_rows, eval_rows,
                               proofs, ns):
        """One batched two-pair pairing dispatch for the whole
        multiproof batch: per row the host folds the interpolation and
        vanishing MSMs into (A, π, Z) limb planes
        (das/poly_proofs.marshal_multiproofs) and the device checks
        e(A, G2_GEN)·e(−π, Z) == 1 through the SAME jitted kernel the
        aggregate-vote path uses — no new kernel, no new compile
        shapes beyond the bucket. Verdicts are bit-identical to the
        scalar PCS reference because every malformed-row rejection and
        every degenerate (infinity-point) row is resolved into the
        planes at marshal time.

        On a mesh layout the planes ship pre-sharded along the leading
        (row) axis and the SAME jitted kernel partitions over them —
        per-row work, so ZERO collectives; `last_mesh` records the
        sharded execution for the non-vacuity checks."""
        from gethsharding_tpu.das import poly_proofs

        jnp = self._jnp
        lay = self._layout
        n = len(commitments)
        if n == 0:
            self.last_wire = None
            return []
        dt = DeviceTimer("das_poly_verify")
        # mesh buckets round up to a device multiple so the
        # NamedSharding split is even; padded rows are marshalled
        # rejections exactly like single-device padding
        bucket = lay.mesh_bucket(n) if lay.is_mesh else self._bucket(n)
        shape = (bucket, lay.n_devices) if lay.is_mesh else (bucket,)
        fresh = self._note_shape("das_poly_verify", *shape)
        st = poly_proofs.marshal_multiproofs(commitments, index_rows,
                                             eval_rows, proofs, ns, bucket)
        planes = (st["px"], st["py"], st["ax"], st["ay"], st["zx"],
                  st["zy"], st["valid"])
        proof_bytes = sum(int(p.nbytes) for p in planes)
        # same wire-ledger contract as the sample path: the marshalled
        # pairing planes ARE this dispatch's host->device bytes
        self.last_wire = {"op": "das_verify_multiproofs",
                          "wire_bytes": proof_bytes,
                          "sample_wire_bytes": proof_bytes,
                          "rows": n, "bucket": bucket, "wire": self._wire}
        RECORDER.record_wire("das_verify_multiproofs", self.last_wire)
        self._m_wire_bytes.inc(proof_bytes)
        tracing.tag_current_add(wire_bytes=proof_bytes,
                                sample_wire_bytes=proof_bytes)
        tracer = tracing.TRACER
        ship = lay.place if lay.is_mesh else jnp.asarray
        dt.dispatched()
        with self._compiles.compile_span("das_poly_verify", shape, fresh):
            out = self._bls(*(ship(p) for p in planes))
        if lay.is_mesh:
            self.last_mesh = {
                "op": "das_verify_multiproofs",
                "n_devices": lay.n_devices, "bucket": bucket,
                "collectives": 0,
                "verdict_devices": len(out.sharding.device_set),
                "vote_total": None,
            }
        res = [bool(b) for b in dt.pull(out)[:n]]
        dt.done()
        if tracer.enabled:
            tracer.record("jax/das_poly_verify_dispatch", dt.t_dispatch,
                          dt.t_done,
                          tags={"rows": n, "bucket": bucket,
                                "compile": "miss" if fresh else "hit",
                                "sample_wire_bytes": proof_bytes,
                                "suspect": dt.suspect,
                                "marshal_ms": round(dt.marshal_s * 1e3, 3),
                                "device_ms": round(dt.device_s * 1e3, 3)})
        return res

    # -- the staged committee path -----------------------------------------
    # marshal (host limbs + cache resolution) -> transfer (host->device)
    # -> dispatch (device, async) -> pull (result()). Explicit stages so
    # the async form overlaps host staging of batch N+1 with batch N's
    # device execution, and so the SIG_TIMING ledger can attribute every
    # boundary.

    def _committee_submit(self, messages, sig_rows, pk_rows,
                          pk_row_keys) -> VerdictFuture:
        if self._layout.is_mesh:
            return self._committee_submit_mesh(messages, sig_rows,
                                               pk_rows, pk_row_keys)
        import time

        import numpy as np

        timing = os.environ.get("GETHSHARDING_SIG_TIMING") == "1"
        if timing:
            # the split must belong to THIS dispatch: a caller that skips
            # the jax committee path (e.g. an empty batch) must read None,
            # not a stale split from a prior audit in the same process
            self.last_timing = None
        dt = DeviceTimer("bls_committee")
        t0 = time.perf_counter()
        jnp = self._jnp
        n = len(messages)
        if n == 0:
            self.last_wire = None
            future = VerdictFuture(lambda: [])
            future.result()
            return future
        st = self._committee_marshal(messages, sig_rows, pk_rows,
                                     pk_row_keys)
        t1 = time.perf_counter()
        args, wire = self._committee_transfer(st)
        if timing:
            # force EVERY host->device transfer to completion before
            # timing the dispatch (plain block_until_ready can no-op
            # under the tunnel plugin). ONE fused pull: stacking a
            # scalar from each buffer into a single device array and
            # pulling that once waits on all nine transfers with a
            # single host round-trip, so transfer_s reflects transfer
            # bandwidth — a per-buffer pull would add 9 sequential
            # tunnel RTTs the untimed production path never pays
            probe = jnp.stack(
                [a.ravel()[0].astype(jnp.int32) for a in args])
            np.asarray(probe)
            t2 = time.perf_counter()
        # the per-dispatch wire ledger is always on (pure nbytes
        # arithmetic, no device sync) — probe-42 transfer attribution
        # must not require the sync-forcing timing mode
        self.last_wire = wire
        RECORDER.record_wire("bls_verify_committees", wire)
        self._m_wire_bytes.inc(wire["wire_bytes"])
        self._m_pk_hit_bytes.inc(wire["pk_hit_bytes"])
        # stamp the enclosing caller span (the notary's notary/audit);
        # SUMMED, so a multi-dispatch span reports total bytes
        tracing.tag_current_add(wire_bytes=wire["wire_bytes"],
                                pk_hit_bytes=wire["pk_hit_bytes"])
        tracer = tracing.TRACER
        marshal_s = t1 - t0  # host marshal: limb planes + cache resolve
        dt.dispatched()  # marshal (incl. transfer staging) closes here
        if st["precomp"]:
            with self._compiles.compile_span(
                    "bls_committee_precomp",
                    (st["bucket"], st["width"], self._wire,
                     st["blocks"]), st["fresh"]):
                # async launch(es): the pipelined form enqueues Miller
                # block k+1 before finalexp block k
                out = self._precomp_launch(args, st["bucket"],
                                           st["blocks"])
        else:
            fn = (self._bls_committee_u16 if self._wire_u16
                  else self._bls_committee)
            with self._compiles.compile_span(
                    "bls_committee",
                    (st["bucket"], st["width"], self._wire), st["fresh"]):
                out = fn(*args)  # async dispatch: returns pre-execution
        # finalize must close over SCALARS, not the marshal dict: `st`
        # pins every host limb plane (MBs per dispatch) until result(),
        # and an overlapped K-period pipeline holds K of them at once
        bucket, width, fresh = st["bucket"], st["width"], st["fresh"]

        def finalize():
            # the checked pull is the barrier: block-vs-pull divergence
            # (the r4 no-op hazard) lands on perfwatch/timer_suspect
            res = [bool(b) for b in dt.pull(out)[:n]]
            dt.done()
            if tracer.enabled:
                # the checked pull above means the span closes only
                # after the dispatch actually executed; on the async
                # path it additionally covers the overlapped wait
                tracer.record(
                    "jax/bls_committee_dispatch", dt.t_dispatch, dt.t_done,
                    tags={"rows": n, "bucket": bucket,
                          "width": width, "wire": self._wire,
                          "compile": "miss" if fresh else "hit",
                          "suspect": dt.suspect,
                          "wire_bytes": wire["wire_bytes"],
                          "pk_hit_bytes": wire["pk_hit_bytes"],
                          "marshal_ms": round(marshal_s * 1e3, 3),
                          "device_ms": round(dt.device_s * 1e3, 3)})
            if timing:
                t3 = time.perf_counter()
                # per-instance: two backends in one process must not
                # clobber each other's split
                self.last_timing = {
                    "prep_s": round(t1 - t0, 4),
                    "transfer_s": round(t2 - t1, 4),
                    "dispatch_s": round(t3 - t2, 4),
                    "rows": n, "width": width,
                    **wire,
                }
            return res

        return VerdictFuture(finalize)

    def _committee_submit_mesh(self, messages, sig_rows, pk_rows,
                               pk_row_keys) -> VerdictFuture:
        """The mesh committee audit: the same marshal -> transfer ->
        dispatch staging, but every plane ships pre-split along the
        shard axis (each device receives ONLY its slab's bytes, resident
        pk rows come from ITS cache shard) and the launch is ONE pjit'd
        `shard_map` step whose vote-total `psum` is the only
        cross-device traffic — counted per compiled executable from the
        AOT HLO into `last_mesh["collectives"]`. Verdicts are
        bit-identical to the single-device path: same kernels, same
        padding semantics, only placement differs."""
        import time

        import numpy as np

        timing = os.environ.get("GETHSHARDING_SIG_TIMING") == "1"
        if timing:
            self.last_timing = None
        dt = DeviceTimer("bls_committee_mesh")
        t0 = time.perf_counter()
        lay = self._layout
        n = len(messages)
        if n == 0:
            self.last_wire = None
            self.last_mesh = None
            future = VerdictFuture(lambda: [])
            future.result()
            return future
        bucket = lay.mesh_bucket(n)
        pad = bucket - n
        width = marshal.committee_width(sig_rows, pk_rows)
        rows = list(pk_rows) + [[]] * pad
        keys = marshal.normalize_row_keys(pk_row_keys, len(rows))
        resident = self._resident and keys is not None
        precomp = self._precomp and resident
        # the compile-cache key includes the device count: re-laying the
        # same process over a different mesh is a fresh XLA program (and
        # the precomp step is its own program again)
        fresh = self._note_shape(
            "bls_committee_mesh_precomp" if precomp
            else "bls_committee_mesh",
            bucket, width, self._wire, lay.n_devices)
        check = os.environ.get("GETHSHARDING_CHECK") == "1"
        host = marshal.committee_host_planes(
            self._bn, messages, sig_rows, pad, width,
            marshal.wire_dtype(self._wire_u16, check))
        st = {"n": n, "bucket": bucket, "pad": pad, "width": width,
              "fresh": fresh, "check": check,
              "pk_rows": sum(1 for r in rows if r),
              "hit_rows": 0, "hit_bytes": 0}
        conv = marshal.wire_converter(self._wire_u16, check)
        hx, hy = conv(host["hx"]), conv(host["hy"])
        sx, sy = conv(host["sx"]), conv(host["sy"])
        sm, hok = host["sm"], host["hok"]
        wire_bytes = (hx.nbytes + hy.nbytes + sx.nbytes + sy.nbytes
                      + sm.nbytes + hok.nbytes)
        if precomp:
            tab, inf, g2_bytes = self._mesh_line_tables(st, rows, keys,
                                                        lay)
        elif resident:
            px, py, pm, g2_bytes = self._mesh_pk_planes(st, rows, keys,
                                                        lay)
        else:
            pxh, pyh, pmh = self._pk_rows_to_limbs(rows, width,
                                                   row_keys=keys)
            pxh, pyh = conv(pxh), conv(pyh)
            g2_bytes = pxh.nbytes + pyh.nbytes + pmh.nbytes
            px, py, pm = lay.place(pxh), lay.place(pyh), lay.place(pmh)
        wire_bytes += g2_bytes
        t1 = time.perf_counter()
        if precomp:
            args = (lay.place(hx), lay.place(hy), lay.place(sx),
                    lay.place(sy), lay.place(sm), tab, inf,
                    lay.place(hok), self._gen_lines_mesh)
        else:
            args = (lay.place(hx), lay.place(hy), lay.place(sx),
                    lay.place(sy), lay.place(sm), px, py, pm,
                    lay.place(hok))
        if timing:
            for a in args:
                a.block_until_ready()
            t2 = time.perf_counter()
        wire = {"wire_bytes": int(wire_bytes),
                "g2_wire_bytes": int(g2_bytes),
                "pk_hit_bytes": int(st["hit_bytes"]),
                "pk_rows": int(st["pk_rows"]),
                "pk_hit_rows": int(st["hit_rows"]),
                "resident": resident, "precomp": precomp,
                "wire": self._wire}
        self.last_wire = wire
        RECORDER.record_wire("bls_verify_committees", wire)
        self._m_wire_bytes.inc(wire["wire_bytes"])
        self._m_pk_hit_bytes.inc(wire["pk_hit_bytes"])
        tracing.tag_current_add(wire_bytes=wire["wire_bytes"],
                                pk_hit_bytes=wire["pk_hit_bytes"])
        tracer = tracing.TRACER
        marshal_s = t1 - t0
        exe_key = (bucket, width, self._wire,
                   "precomp" if precomp else "recompute")
        mesh_fn = (self._bls_committee_mesh_precomp if precomp
                   else self._bls_committee_mesh)
        dt.dispatched()
        with self._compiles.compile_span(
                "bls_committee_mesh_precomp" if precomp
                else "bls_committee_mesh",
                (bucket, width, self._wire, lay.n_devices), fresh):
            exe = self._mesh_exec.get(exe_key)
            if exe is None:
                # AOT: one .lower().compile() gives the executable AND
                # its optimized HLO, so the one-collective assertion is
                # a free byproduct of the compile we had to do anyway
                exe = mesh_fn.lower(*args).compile()
                self._mesh_exec[exe_key] = exe
                self._mesh_collectives[exe_key] = \
                    layout_mod.count_collectives(exe.as_text())
            out, votes = exe(*args)
        collectives = self._mesh_collectives[exe_key]
        mesh_rec = {"op": "bls_verify_committees",
                    "n_devices": lay.n_devices, "bucket": bucket,
                    "width": width, "collectives": collectives,
                    "precomp": precomp,
                    "verdict_devices": None, "vote_total": None}
        self.last_mesh = mesh_rec

        def finalize():
            res = [bool(b) for b in dt.pull(out)[:n]]
            # non-vacuity evidence for the tests/bench: the verdict
            # plane really was sharded over the mesh, and the psum'd
            # vote total agrees with the verdict plane it reduced
            mesh_rec["verdict_devices"] = len(out.sharding.device_set)
            mesh_rec["vote_total"] = int(np.asarray(votes))
            dt.done()
            if tracer.enabled:
                tracer.record(
                    "jax/bls_committee_mesh_dispatch", dt.t_dispatch,
                    dt.t_done,
                    tags={"rows": n, "bucket": bucket, "width": width,
                          "wire": self._wire,
                          "n_devices": lay.n_devices,
                          "collectives": collectives,
                          "compile": "miss" if fresh else "hit",
                          "suspect": dt.suspect,
                          "wire_bytes": wire["wire_bytes"],
                          "pk_hit_bytes": wire["pk_hit_bytes"],
                          "marshal_ms": round(marshal_s * 1e3, 3),
                          "device_ms": round(dt.device_s * 1e3, 3)})
            if timing:
                t3 = time.perf_counter()
                self.last_timing = {
                    "prep_s": round(t1 - t0, 4),
                    "transfer_s": round(t2 - t1, 4),
                    "dispatch_s": round(t3 - t2, 4),
                    "rows": n, "width": width,
                    **wire,
                }
            return res

        return VerdictFuture(finalize)

    def _committee_marshal(self, messages, sig_rows, pk_rows,
                           pk_row_keys) -> dict:
        """Stage 1, host only: padding policy, limb marshalling of the
        fresh-per-period buffers (hashes, signatures, masks), pk-row
        cache resolution (device hits claimed, misses marshalled)."""
        n = len(messages)
        bucket = self._bucket(n)
        pad = bucket - n
        width = marshal.committee_width(sig_rows, pk_rows)
        rows = list(pk_rows) + [[]] * pad
        keys = marshal.normalize_row_keys(pk_row_keys, len(rows))
        resident = self._resident and keys is not None
        # the precomp path needs the resident LRU (line tables are its
        # residents) — keyless or resident-off dispatches fall back to
        # the recompute kernel, today's path bit-for-bit
        precomp = self._precomp and resident
        blocks = self._precomp_nblocks(bucket) if precomp else 0
        # the compile-cache key INCLUDES the wire dtype: the u16 wire
        # compiles a different XLA program for the same (bucket, width),
        # so counting it against the other wire's entry would book a
        # real recompile as a hit. The precomp path is its own op (line
        # tables in, no G2 planes, its own block pipeline).
        if precomp:
            fresh = self._note_shape("bls_committee_precomp", bucket,
                                     width, self._wire, blocks)
        else:
            fresh = self._note_shape("bls_committee", bucket, width,
                                     self._wire)
        check = os.environ.get("GETHSHARDING_CHECK") == "1"
        host = marshal.committee_host_planes(
            self._bn, messages, sig_rows, pad, width,
            marshal.wire_dtype(self._wire_u16, check))
        st = {"n": n, "bucket": bucket, "pad": pad, "width": width,
              "fresh": fresh, "check": check,
              "pk_rows": sum(1 for r in rows if r),
              "hx": host["hx"], "hy": host["hy"], "hok": host["hok"],
              "sx": host["sx"], "sy": host["sy"], "sm": host["sm"],
              "resident": resident, "precomp": precomp,
              "blocks": blocks}
        if precomp:
            self._line_resolve(st, rows, keys)
        elif resident:
            self._pk_resident_resolve(st, rows, keys)
        else:
            px, py, pm = self._pk_rows_to_limbs(rows, width, row_keys=keys)
            st["px"], st["py"], st["pm"] = px, py, pm
        return st

    def _committee_transfer(self, st) -> tuple:
        """Stage 2, host->device: ship the fresh-per-period buffers (+
        any pk-row misses) and assemble the kernel args. Returns
        (args, wire_ledger); ledger bytes are LOGICAL wire bytes — what
        crosses the host->device link for this dispatch. Device-cache
        hits and on-device stacking contribute zero."""
        jnp = self._jnp
        conv = marshal.wire_converter(self._wire_u16, st["check"])
        hx, hy = conv(st["hx"]), conv(st["hy"])
        sx, sy = conv(st["sx"]), conv(st["sy"])
        sm, hok = st["sm"], st["hok"]
        wire_bytes = (hx.nbytes + hy.nbytes + sx.nbytes + sy.nbytes
                      + sm.nbytes + hok.nbytes)
        if st["precomp"]:
            # line tables replace the pk planes entirely: warm rows
            # ship NOTHING (g2_bytes counts only cold precompute input)
            tab, inf, g2_bytes = self._line_tables(st)
            hit_bytes, hit_rows = st["hit_bytes"], st["hit_rows"]
            args = (jnp.asarray(hx), jnp.asarray(hy), jnp.asarray(sx),
                    jnp.asarray(sy), jnp.asarray(sm), tab, inf,
                    jnp.asarray(hok))
        else:
            if st["resident"]:
                px, py, pm, g2_bytes = self._pk_resident_planes(st)
                hit_bytes, hit_rows = st["hit_bytes"], st["hit_rows"]
            else:
                pxh, pyh, pmh = conv(st["px"]), conv(st["py"]), st["pm"]
                g2_bytes = pxh.nbytes + pyh.nbytes + pmh.nbytes
                px, py, pm = (jnp.asarray(pxh), jnp.asarray(pyh),
                              jnp.asarray(pmh))
                hit_bytes = hit_rows = 0
            args = (jnp.asarray(hx), jnp.asarray(hy), jnp.asarray(sx),
                    jnp.asarray(sy), jnp.asarray(sm), px, py, pm,
                    jnp.asarray(hok))
        wire_bytes += g2_bytes
        wire = {"wire_bytes": int(wire_bytes),
                "g2_wire_bytes": int(g2_bytes),
                "pk_hit_bytes": int(hit_bytes),
                "pk_rows": int(st["pk_rows"]),
                "pk_hit_rows": int(hit_rows),
                "resident": st["resident"],
                "precomp": st["precomp"],
                "blocks": (int(st["blocks"]) if st["precomp"] else None),
                "wire": self._wire}
        return args, wire

    # populated by bls_verify_committees under GETHSHARDING_SIG_TIMING=1:
    # host marshalling vs tunnel transfer vs device dispatch of the LAST
    # audit call (+ the wire ledger) — the split that decides which side
    # of the dispatch boundary the next optimization belongs to
    last_timing: dict | None = None

    # populated by EVERY committee dispatch (no sync, pure nbytes
    # arithmetic): {wire_bytes, g2_wire_bytes, pk_hit_bytes, pk_rows,
    # pk_hit_rows, resident, precomp, wire} — the transfer-attribution
    # ledger bench.py records per config and the residency/precomp
    # tests assert on (steady state: g2_wire_bytes == 0; precomp True
    # when the dispatch consumed resident line tables)
    last_wire: dict | None = None

    # populated by every MESH dispatch: {op, n_devices, bucket, width,
    # collectives, verdict_devices, vote_total} — the non-vacuity
    # evidence (the pjit path really produced sharded arrays; exactly
    # one cross-device collective per committee step). None on
    # single-device layouts.
    last_mesh: dict | None = None
