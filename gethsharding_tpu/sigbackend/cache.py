"""Resident pk-plane caches: host row cache, device LRU, batch memo —
and the per-device shards of the mesh layout.

Committee PUBKEYS recur period after period (registered keys are
stable until release) while signatures are fresh every vote — so the
G2 half of the audit's marshalling cost, the largest, is cacheable at
three levels:

- **host row cache** (`_pk_rows_to_limbs`): removes the limb
  CONVERSION from a warm audit (FIFO, `_PK_ROW_CACHE_MAX` rows);
- **device-resident LRU** (`GETHSHARDING_TPU_RESIDENT`, default on):
  removes the TRANSFER — per-row device buffers keyed by
  (pk_row_key, width, wire) under a memory-accounted LRU bounded by
  ``GETHSHARDING_TPU_RESIDENT_MB``;
- **batch memo**: the steady-state audit repeats the SAME row-key
  tuple every period, so the stacked kernel planes are reused whole —
  zero transfers AND zero per-dispatch device stacking ops.

Under `GETHSHARDING_PRECOMP` a fourth resident kind joins the SAME
byte-budgeted device LRU: per-row Miller line-coefficient TABLES
(`(key, "lines")` entries, `ops/bn256_jax.precompute_lines` output).
A cold row pays one precompute dispatch; every warm audit then ships
zero G2 bytes AND skips the fixed-argument point arithmetic entirely.
Tables are keyed by `pk_row_key` alone — the on-device aggregate is a
function of row content only, so one table serves every committee
width and wire dtype. Entries are charged at their TRUE device byte
count (int32 tables even under the u16 wire — a plane-shape estimate
would under-charge ~2x and trip devscope's claimed-vs-census drift
gate).

On a mesh layout the device LRU becomes PER-DEVICE SHARDS
(`MeshCacheShard`): each mesh slot owns an independent LRU holding
only the rows its slab consumes, with its own byte budget (an equal
split of the resident budget), its own hit/miss/evict counters and
HBM gauge (``jax/pk_device_cache/shard<i>/*``), and its own devscope
census owner (``pk_plane_lru_shard<i>``) — so the census attributes
every resident byte to the device that actually holds it, and the
owners are disjoint by construction.

`ResidentPkCache` is mixed into `JaxSigBackend` (dispatch.py): state
lives on the backend instance under the SAME attribute names the
pre-split backend used, so the residency tests and the devscope
census cross-check compose unchanged.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from gethsharding_tpu import metrics
from gethsharding_tpu.sigbackend import marshal


class MeshCacheShard:
    """One device's slice of the resident pk plane: its own LRU, byte
    budget, gauges and devscope census owner (registered by the
    mixin). All mutation happens under the owning backend's mesh lock;
    the shard itself is a dumb record."""

    __slots__ = ("index", "device", "budget", "cache", "bytes",
                 "zero_rows", "m_hit", "m_miss", "m_evict", "g_bytes")

    def __init__(self, index: int, device, budget: int):
        self.index = index
        self.device = device
        self.budget = budget
        self.cache: OrderedDict = OrderedDict()
        self.bytes = 0
        self.zero_rows: dict = {}  # (width, wire) -> device zero planes
        prefix = f"jax/pk_device_cache/shard{index}"
        self.m_hit = metrics.counter(prefix + "/hits")
        self.m_miss = metrics.counter(prefix + "/misses")
        self.m_evict = metrics.counter(prefix + "/evictions")
        self.g_bytes = metrics.gauge(prefix + "/bytes")


class ResidentPkCache:
    """The cache half of `JaxSigBackend` (a mixin: state lands on the
    backend instance so existing attribute contracts hold)."""

    # rows; an entry holds BOTH coordinate arrays: ~54 KB at 135x(2,25)
    # int32, so 1024 rows cap the cache near 55 MB (production needs at
    # most one row per shard in the steady state)
    _PK_ROW_CACHE_MAX = 1024

    _pk_batch_memo_nbytes = 0
    _pk_line_memo_nbytes = 0
    # class default so census callbacks work on a backend whose
    # __init__ predates the line memo (devscope's partial-construction
    # registration path)
    _pk_line_memo = None

    def _init_pk_caches(self) -> None:
        """Construct the cache state (called from the backend's
        __init__; the backend is a process-wide singleton shared by
        every actor thread, so every structure is lock-guarded)."""
        self._pk_row_cache: dict = {}
        self._pk_row_lock = threading.Lock()
        self._resident = os.environ.get(
            "GETHSHARDING_TPU_RESIDENT", "1") != "0"
        self._resident_budget = int(float(os.environ.get(
            "GETHSHARDING_TPU_RESIDENT_MB", "256")) * (1 << 20))
        self._pk_dev_cache: OrderedDict = OrderedDict()
        self._pk_dev_bytes = 0
        self._pk_dev_lock = threading.Lock()
        self._pk_batch_memo: "tuple | None" = None  # (key, planes, nbytes)
        self._pk_line_memo: "tuple | None" = None  # (key, (tab, inf), bytes)
        self._pk_zero_rows: dict = {}  # width -> device zero row planes
        self._m_row_hit = metrics.counter("jax/pk_row_cache/hits")
        self._m_row_miss = metrics.counter("jax/pk_row_cache/misses")
        self._m_dev_hit = metrics.counter("jax/pk_device_cache/hits")
        self._m_dev_miss = metrics.counter("jax/pk_device_cache/misses")
        self._m_dev_evict = metrics.counter("jax/pk_device_cache/evictions")
        self._g_dev_bytes = metrics.gauge("jax/pk_device_cache/bytes")
        # mesh state (filled by _init_mesh_shards on mesh layouts)
        self._mesh_shards: list = []
        self._mesh_memo: "tuple | None" = None
        self._mesh_line_memo: "tuple | None" = None
        self._mesh_lock = threading.Lock()

    def _register_census_owner(self) -> None:
        """Register the resident plane as a devscope census owner so
        the poller can cross-check the cache's OWN byte accounting
        against what the device actually holds (drift beyond tolerance
        -> devscope/mem/drift). The registration holds a WEAK ref: the
        owner registry is module-global and must not pin a discarded
        backend (and its device LRU) alive; a dead ref reads as an
        empty owner. Latest instance wins the name — the registry
        backend is a process singleton (get_backend cache), so
        replacement only happens in tests building instances
        directly."""
        import weakref

        from gethsharding_tpu import devscope

        self_ref = weakref.ref(self)

        def _claimed() -> int:
            backend = self_ref()
            return (0 if backend is None
                    else backend._resident_claimed_bytes())

        def _buffers() -> list:
            backend = self_ref()
            return [] if backend is None else backend._resident_buffers()

        devscope.register_owner("pk_plane_lru", claimed_fn=_claimed,
                                buffers_fn=_buffers)

    def _resident_claimed_bytes(self) -> int:
        """The resident plane's own accounting — the number the
        devscope census is cross-checked against. Covers exactly what
        `_resident_buffers` censuses: cache entries + batch memo +
        the shared zero rows (never evicted, outside the LRU budget —
        counting them on one side only would read as permanent
        drift)."""
        zero = sum(int(b.nbytes)
                   for row in self._pk_zero_rows.copy().values()
                   for b in row)
        gen = getattr(self, "_gen_lines_dev", None)
        if gen is not None:
            zero += int(gen.nbytes)
        with self._pk_dev_lock:
            return (self._pk_dev_bytes + self._pk_batch_memo_nbytes
                    + self._pk_line_memo_nbytes + zero)

    def _resident_buffers(self) -> list:
        """Every device buffer the resident plane holds (cache rows,
        the batch/line memos, the shared zero rows, the resident
        generator line table) for census attribution."""
        out: list = []
        with self._pk_dev_lock:
            for entry in self._pk_dev_cache.values():
                # line-table entries pad slot 2 with None (no third
                # buffer) to keep the pk-plane entry shape
                out.extend(b for b in entry[:3] if b is not None)
            memo = self._pk_batch_memo
            line_memo = self._pk_line_memo
        if memo is not None:
            out.extend(memo[1])
        if line_memo is not None:
            out.extend(line_memo[1])
        # .copy(): atomic snapshot — _zero_pk_row publishes new rows
        # without the dev lock, and a mid-iteration insert would raise
        for row in self._pk_zero_rows.copy().values():
            out.extend(row)
        gen = getattr(self, "_gen_lines_dev", None)
        if gen is not None:
            out.append(gen)
        return out

    # -- pubkey-row limb cache (host) --------------------------------------
    # Caching is per ROW keyed by caller-supplied hashable keys (the
    # notary passes the wire hex strings, whose hashes python interns):
    # per-POINT value keys were tried and the 13k bigint-tuple hashes
    # per audit cost as much as the conversion they saved.

    def _pk_rows_to_limbs(self, rows, width: int, row_keys=None):
        import numpy as np

        if row_keys is None:
            return self._bn.g2_committee_to_limbs(rows, width)
        cache = self._pk_row_cache
        nl = int(np.asarray(self._bn.FP.one).shape[-1])
        B = len(rows)
        # under the u16 wire the pk planes — the audit's largest buffers
        # — are assembled (and cached) as uint16 at MISS time, so cache
        # hits skip the narrowing copy entirely (limbs are 12-bit)
        dtype = np.uint16 if self._wire_u16 else np.int32
        xs = np.zeros((B, width, 2, nl), dtype)
        ys = np.zeros((B, width, 2, nl), dtype)
        mask = np.zeros((B, width), bool)
        misses = []  # (b, key, row) — bulk-converted in ONE pass below
        hits = 0
        for b, row in enumerate(rows):
            if len(row) > width:
                raise ValueError(
                    f"committee of {len(row)} exceeds width {width}")
            if not row:
                continue
            key = row_keys[b] if b < len(row_keys) else None
            if key is None:
                entry = None
            else:
                with self._pk_row_lock:
                    entry = cache.get(key)
            if entry is None:
                misses.append((b, key, row))
                continue
            hits += 1
            k = entry[0].shape[0]
            xs[b, :k], ys[b, :k], mask[b, :k] = entry
        self._m_row_hit.inc(hits)
        self._m_row_miss.inc(sum(1 for _, key, _ in misses
                                 if key is not None))
        if misses:
            # one bulk bit-plane conversion for every miss row (a cold
            # audit would otherwise pay the fixed numpy overhead per
            # row), emitted straight into the wire dtype
            miss_w = max(len(row) for _, _, row in misses)
            mx, my, mm = self._bn.g2_committee_to_limbs(
                [row for _, _, row in misses], miss_w, out_dtype=dtype)
            for i, (b, key, row) in enumerate(misses):
                k = len(row)
                xs[b, :k] = mx[i, :k]
                ys[b, :k] = my[i, :k]
                mask[b, :k] = mm[i, :k]
                if key is not None:
                    with self._pk_row_lock:
                        while len(cache) >= self._PK_ROW_CACHE_MAX:
                            # FIFO: evict one stale row, not all of them
                            cache.pop(next(iter(cache)))
                        # copies, not views: a view would pin the whole
                        # bulk conversion array per cached row (astype
                        # copies even at the same dtype)
                        cache[key] = (mx[i, :k].astype(dtype),
                                      my[i, :k].astype(dtype),
                                      mm[i, :k].copy())
        return xs, ys, mask

    # -- device-resident pk planes (single-device LRU) ---------------------

    def _pk_resident_resolve(self, st: dict, rows, keys) -> None:
        """Host half of the resident path: claim device-cache hits,
        bulk-marshal miss rows (through the host row cache). A pointful
        row without a key is uncacheable — transferred every dispatch;
        an empty row maps to the shared on-device zero planes."""
        width, wire = st["width"], self._wire
        # the batch memo is only sound when every pointful row is keyed
        # (a keyless row's contents are not determined by the key tuple)
        if all(k is not None or not row for row, k in zip(rows, keys)):
            batch_key = (tuple(keys), st["bucket"], width, wire)
        else:
            batch_key = None
        st["batch_key"] = batch_key
        with self._pk_dev_lock:
            memo = self._pk_batch_memo
        if batch_key is not None and memo is not None \
                and memo[0] == batch_key:
            st["memo_planes"] = memo[1]
            st["hit_rows"] = st["pk_rows"]
            st["hit_bytes"] = memo[2]
            st["miss_planes"] = None
            self._m_dev_hit.inc(st["pk_rows"])
            return
        st["memo_planes"] = None
        plan = []  # per row: ("zero",) | ("hit", entry) | ("miss", j)
        misses = []  # (row, key)
        hit_rows = hit_bytes = 0
        with self._pk_dev_lock:
            cache = self._pk_dev_cache
            for row, key in zip(rows, keys):
                if not row:
                    plan.append(("zero",))
                    continue
                entry = None
                if key is not None:
                    entry = cache.get((key, width, wire))
                    if entry is not None:
                        cache.move_to_end((key, width, wire))
                if entry is not None:
                    plan.append(("hit", entry))
                    hit_rows += 1
                    hit_bytes += entry[3]
                else:
                    plan.append(("miss", len(misses)))
                    misses.append((row, key))
        self._m_dev_hit.inc(hit_rows)
        self._m_dev_miss.inc(len(misses))
        st["plan"] = plan
        st["hit_rows"], st["hit_bytes"] = hit_rows, hit_bytes
        if misses:
            # bulk conversion at the dispatch width, through the HOST
            # row cache: a device-evicted row re-transfers but does not
            # re-pay the bit-plane conversion
            mx, my, mm = self._pk_rows_to_limbs(
                [row for row, _ in misses], width,
                row_keys=[key for _, key in misses])
            st["miss_planes"] = (mx, my, mm)
            st["miss_keys"] = [key for _, key in misses]
        else:
            st["miss_planes"] = None

    def _pk_resident_planes(self, st: dict):
        """Device half: ship miss rows, stack hits + misses + zeros into
        the (B, width, 2, nl) kernel planes. Returns (px, py, pm,
        transferred_g2_bytes)."""
        jnp = self._jnp
        if st["memo_planes"] is not None:
            px, py, pm = st["memo_planes"]
            return px, py, pm, 0

        miss_dev = []
        g2_bytes = 0
        if st["miss_planes"] is not None:
            mx, my, mm = st["miss_planes"]
            if st["check"] and self._wire_u16 and mx.size:
                # the u16 invariant, pinned once per row AT SHIP TIME
                # (hit rows were checked when first transferred)
                marshal.assert_canonical_limbs(mx, my)
            # ONE bulk transfer for ALL miss rows (the planes are already
            # contiguous); the cache entries are per-row device slices —
            # device-side ops, not M separate host->device round trips
            dmx, dmy, dmm = (jnp.asarray(mx), jnp.asarray(my),
                             jnp.asarray(mm))
            g2_bytes = mx.nbytes + my.nbytes + mm.nbytes
            for j, key in enumerate(st["miss_keys"]):
                nbytes = mx[j].nbytes + my[j].nbytes + mm[j].nbytes
                entry = (dmx[j], dmy[j], dmm[j], nbytes)
                if key is not None:
                    self._pk_dev_insert(
                        (key, st["width"], self._wire), entry)
                miss_dev.append(entry)
        zx, zy, zm = self._zero_pk_row(st["width"])
        xs, ys, ms = [], [], []
        for step in st["plan"]:
            if step[0] == "zero":
                entry = (zx, zy, zm)
            elif step[0] == "hit":
                entry = step[1]
            else:
                entry = miss_dev[step[1]]
            xs.append(entry[0])
            ys.append(entry[1])
            ms.append(entry[2])
        # device-side assembly: concatenation of resident buffers, no
        # host bytes on the link
        px, py, pm = jnp.stack(xs), jnp.stack(ys), jnp.stack(ms)
        if st["batch_key"] is not None:
            # memoize the assembled batch; its hit ledger is what THIS
            # assembly would have cost over the wire
            self._set_batch_memo(st["batch_key"], (px, py, pm),
                                 st["hit_bytes"] + g2_bytes)
        return px, py, pm, g2_bytes

    def _pk_dev_insert(self, key, entry) -> None:
        """LRU insert with byte-accounted eviction (gauge + counter)."""
        with self._pk_dev_lock:
            cache = self._pk_dev_cache
            if key in cache:
                cache.move_to_end(key)
                return
            cache[key] = entry
            self._pk_dev_bytes += entry[3]
            while self._pk_dev_bytes > self._resident_budget and cache:
                _, old = cache.popitem(last=False)
                self._pk_dev_bytes -= old[3]
                self._m_dev_evict.inc()
            self._g_dev_bytes.set(
                self._pk_dev_bytes + self._pk_batch_memo_nbytes
                + self._pk_line_memo_nbytes)

    def _set_batch_memo(self, key, planes, hit_bytes) -> None:
        px, py, pm = planes
        with self._pk_dev_lock:
            self._pk_batch_memo = (key, planes, hit_bytes)
            self._pk_batch_memo_nbytes = px.nbytes + py.nbytes + pm.nbytes
            self._g_dev_bytes.set(
                self._pk_dev_bytes + self._pk_batch_memo_nbytes
                + self._pk_line_memo_nbytes)

    def _zero_pk_row(self, width: int):
        """Shared on-device zero planes for empty/padded rows (mask all
        False -> the kernel rejects the row, scalar parity) — created
        once per (width, wire), never transferred per dispatch."""
        import numpy as np

        key = (width, self._wire)
        row = self._pk_zero_rows.get(key)
        if row is None:
            jnp = self._jnp
            nl = int(np.asarray(self._bn.FP.one).shape[-1])
            dtype = np.uint16 if self._wire_u16 else np.int32
            row = (jnp.zeros((width, 2, nl), dtype),
                   jnp.zeros((width, 2, nl), dtype),
                   jnp.zeros((width,), bool))
            self._pk_zero_rows[key] = row
        return row

    # -- device-resident line tables (fixed-base precomp) ------------------
    # The precompute path's residents: per pk_row_key the dense Miller
    # line-coefficient table (L, 3, 2, nl) int32 + its infinity flag,
    # sharing the pk-plane LRU (one byte budget, one eviction order).
    # Entries are (table, inf, None, nbytes): the None pads to the
    # pk-plane entry shape so the census walks both kinds; nbytes is the
    # TRUE device byte count of the int32 table (under the u16 wire a
    # plane-shape estimate would under-charge ~2x and trip the devscope
    # claimed-vs-census drift gate). Tables are keyed `(key, "lines")` —
    # content only: the aggregate is width/wire-independent as a GROUP
    # element, so verdicts are exact for any consumer; the projective
    # REPRESENTATIVE (and hence raw f bits) matches the recompute path
    # when the table was built at the same dispatch width.

    def _zero_line_row(self):
        """Shared on-device zero line table for empty rows: inf=True ->
        the precomp kernel rejects the row, matching the recompute
        kernel's `fp2_is_zero(pZ)` rejection (scalar parity)."""
        import numpy as np

        row = self._pk_zero_rows.get("lines")
        if row is None:
            jnp = self._jnp
            row = (jnp.zeros(self._bn.LINE_TABLE_SHAPE, np.int32),
                   jnp.asarray(True))
            self._pk_zero_rows["lines"] = row
        return row

    def _line_resolve(self, st: dict, rows, keys) -> None:
        """Host half of the precomp path: claim line-table hits, plan
        misses (whose pk planes alone are marshalled — hit rows ship
        NOTHING, not even the pk plane the recompute path would need)."""
        width = st["width"]
        if all(k is not None or not row for row, k in zip(rows, keys)):
            batch_key = (tuple(keys), st["bucket"], "precomp")
        else:
            batch_key = None
        st["line_key"] = batch_key
        with self._pk_dev_lock:
            memo = self._pk_line_memo
        if batch_key is not None and memo is not None \
                and memo[0] == batch_key:
            st["line_memo"] = memo[1]
            st["hit_rows"] = st["pk_rows"]
            st["hit_bytes"] = memo[2]
            st["line_miss"] = None
            self._m_dev_hit.inc(st["pk_rows"])
            return
        st["line_memo"] = None
        plan = []  # per row: ("zero",) | ("hit", entry) | ("miss", j)
        misses = []  # (row, key)
        hit_rows = hit_bytes = 0
        with self._pk_dev_lock:
            cache = self._pk_dev_cache
            for row, key in zip(rows, keys):
                if not row:
                    plan.append(("zero",))
                    continue
                entry = None
                if key is not None:
                    entry = cache.get((key, "lines"))
                    if entry is not None:
                        cache.move_to_end((key, "lines"))
                if entry is not None:
                    plan.append(("hit", entry))
                    hit_rows += 1
                    hit_bytes += entry[3]
                else:
                    plan.append(("miss", len(misses)))
                    misses.append((row, key))
        self._m_dev_hit.inc(hit_rows)
        self._m_dev_miss.inc(len(misses))
        st["line_plan"] = plan
        st["hit_rows"], st["hit_bytes"] = hit_rows, hit_bytes
        if misses:
            mx, my, mm = self._pk_rows_to_limbs(
                [row for row, _ in misses], width,
                row_keys=[key for _, key in misses])
            st["line_miss"] = (mx, my, mm)
            st["line_miss_keys"] = [key for _, key in misses]
        else:
            st["line_miss"] = None

    def _line_tables(self, st: dict):
        """Device half of the precomp path: ONE precompute dispatch
        walks the fixed-argument point arithmetic for ALL miss rows
        (cold cost, paid once per key), then hits + misses + zeros stack
        into the (B, L, 3, 2, nl) table plane + (B,) infinity flags.
        Returns (table, inf, transferred_g2_bytes)."""
        jnp = self._jnp
        if st["line_memo"] is not None:
            tab, inf = st["line_memo"]
            return tab, inf, 0
        miss_dev = []
        g2_bytes = 0
        if st["line_miss"] is not None:
            mx, my, mm = st["line_miss"]
            if st["check"] and self._wire_u16 and mx.size:
                marshal.assert_canonical_limbs(mx, my)
            dmx, dmy, dmm = (jnp.asarray(mx), jnp.asarray(my),
                             jnp.asarray(mm))
            g2_bytes = mx.nbytes + my.nbytes + mm.nbytes
            tabs, infs = self._precompute(dmx, dmy, dmm)
            for j, key in enumerate(st["line_miss_keys"]):
                nbytes = int(tabs[j].nbytes) + int(infs[j].nbytes)
                entry = (tabs[j], infs[j], None, nbytes)
                if key is not None:
                    self._pk_dev_insert((key, "lines"), entry)
                miss_dev.append(entry)
        zt, zi = self._zero_line_row()
        ts, fs = [], []
        for step in st["line_plan"]:
            if step[0] == "zero":
                entry = (zt, zi)
            elif step[0] == "hit":
                entry = step[1]
            else:
                entry = miss_dev[step[1]]
            ts.append(entry[0])
            fs.append(entry[1])
        tab, inf = jnp.stack(ts), jnp.stack(fs)
        if st["line_key"] is not None:
            with self._pk_dev_lock:
                self._pk_line_memo = (st["line_key"], (tab, inf),
                                      st["hit_bytes"] + g2_bytes)
                self._pk_line_memo_nbytes = (int(tab.nbytes)
                                             + int(inf.nbytes))
                self._g_dev_bytes.set(
                    self._pk_dev_bytes + self._pk_batch_memo_nbytes
                    + self._pk_line_memo_nbytes)
        return tab, inf, g2_bytes

    # -- per-device mesh shards --------------------------------------------

    def _init_mesh_shards(self, layout) -> None:
        """One `MeshCacheShard` per mesh slot: an equal split of the
        resident byte budget, per-shard gauges, and a per-shard
        devscope census owner (disjoint by construction: a shard holds
        only buffers committed to ITS device)."""
        import weakref

        from gethsharding_tpu import devscope

        per_device = max(1, self._resident_budget // layout.n_devices)
        self._mesh_shards = [MeshCacheShard(i, dev, per_device)
                             for i, dev in enumerate(layout.devices)]
        self_ref = weakref.ref(self)
        for shard in self._mesh_shards:
            idx = shard.index

            def _claimed(idx=idx) -> int:
                backend = self_ref()
                return (0 if backend is None
                        else backend._mesh_claimed_bytes(idx))

            def _buffers(idx=idx) -> list:
                backend = self_ref()
                return ([] if backend is None
                        else backend._mesh_shard_buffers(idx))

            devscope.register_owner(f"pk_plane_lru_shard{idx}",
                                    claimed_fn=_claimed,
                                    buffers_fn=_buffers)

    def _mesh_claimed_bytes(self, idx: int) -> int:
        """Shard `idx`'s own accounting: its LRU bytes + its zero rows
        + its equal slice of the (leading-axis-sharded) batch memo."""
        shard = self._mesh_shards[idx]
        with self._mesh_lock:
            total = shard.bytes
            memo = self._mesh_memo
            line_memo = self._mesh_line_memo
            zero = sum(int(b.nbytes)
                       for row in shard.zero_rows.values() for b in row)
        total += zero
        if memo is not None:
            total += memo[3] // max(1, len(self._mesh_shards))
        if line_memo is not None:
            total += line_memo[3] // max(1, len(self._mesh_shards))
        return total

    def _mesh_shard_buffers(self, idx: int) -> list:
        """Every device buffer shard `idx` holds — its LRU entries and
        zero rows, plus this device's addressable slice of the memoized
        global planes — for census attribution."""
        shard = self._mesh_shards[idx]
        out: list = []
        with self._mesh_lock:
            for entry in shard.cache.values():
                out.extend(b for b in entry[:3] if b is not None)
            memo = self._mesh_memo
            line_memo = self._mesh_line_memo
            zero_rows = list(shard.zero_rows.values())
        for row in zero_rows:
            out.extend(row)
        for m in (memo, line_memo):
            if m is not None:
                for arr in m[1]:
                    for piece in arr.addressable_shards:
                        if piece.device == shard.device:
                            out.append(piece.data)
        return out

    def _mesh_zero_row(self, shard: MeshCacheShard, width: int):
        """Shard-local zero planes (the `_zero_pk_row` contract, but
        committed to the shard's device so the per-device stack stays
        on-device)."""
        import numpy as np

        key = (width, self._wire)
        with self._mesh_lock:
            row = shard.zero_rows.get(key)
        if row is None:
            import jax

            nl = int(np.asarray(self._bn.FP.one).shape[-1])
            dtype = np.uint16 if self._wire_u16 else np.int32
            row = tuple(
                jax.device_put(z, shard.device)
                for z in (np.zeros((width, 2, nl), dtype),
                          np.zeros((width, 2, nl), dtype),
                          np.zeros((width,), bool)))
            with self._mesh_lock:
                shard.zero_rows.setdefault(key, row)
                row = shard.zero_rows[key]
        return row

    def _mesh_shard_insert(self, shard: MeshCacheShard, key,
                           entry) -> None:
        """Per-shard LRU insert with byte-accounted eviction: the
        shard's counters AND the process-wide eviction counter tick, so
        single-device dashboards keep reading."""
        with self._mesh_lock:
            cache = shard.cache
            if key in cache:
                cache.move_to_end(key)
                return
            cache[key] = entry
            shard.bytes += entry[3]
            while shard.bytes > shard.budget and cache:
                _, old = cache.popitem(last=False)
                shard.bytes -= old[3]
                shard.m_evict.inc()
                self._m_dev_evict.inc()
            shard.g_bytes.set(shard.bytes)

    def _mesh_pk_planes(self, st: dict, rows, keys, layout):
        """The mesh resident path: resolve every (padded) batch row
        against ITS device's cache shard, ship misses only to their
        owning device, stack per-device slabs on-device and assemble
        the global `NamedSharding(P('shard'))` planes with zero
        cross-device traffic. Returns (px, py, pm, transferred
        g2_bytes); fills st["hit_rows"/"hit_bytes"/"batch_key"]."""
        import jax

        jnp = self._jnp
        width, wire, bucket = st["width"], self._wire, st["bucket"]
        rpd = layout.rows_per_device(bucket)
        if keys is not None and all(
                k is not None or not row for row, k in zip(rows, keys)):
            batch_key = (tuple(keys), bucket, width, wire,
                         layout.n_devices)
        else:
            batch_key = None
        st["batch_key"] = batch_key
        with self._mesh_lock:
            memo = self._mesh_memo
        if batch_key is not None and memo is not None \
                and memo[0] == batch_key:
            px, py, pm = memo[1]
            st["hit_rows"] = st["pk_rows"]
            st["hit_bytes"] = memo[2]
            self._m_dev_hit.inc(st["pk_rows"])
            return px, py, pm, 0

        per_x, per_y, per_m = [], [], []
        g2_bytes = hit_rows = hit_bytes = miss_rows = 0
        for shard in self._mesh_shards:
            lo = shard.index * rpd
            s_rows = rows[lo:lo + rpd]
            s_keys = (keys[lo:lo + rpd] if keys is not None
                      else [None] * len(s_rows))
            plan = []  # ("zero",) | ("hit", entry) | ("miss", j)
            misses = []  # (row, key)
            with self._mesh_lock:
                for row, key in zip(s_rows, s_keys):
                    if not row:
                        plan.append(("zero",))
                        continue
                    entry = None
                    if key is not None:
                        entry = shard.cache.get((key, width, wire))
                        if entry is not None:
                            shard.cache.move_to_end((key, width, wire))
                    if entry is not None:
                        plan.append(("hit", entry))
                        hit_rows += 1
                        hit_bytes += entry[3]
                        shard.m_hit.inc()
                    else:
                        plan.append(("miss", len(misses)))
                        misses.append((row, key))
                        shard.m_miss.inc()
            miss_dev = []
            if misses:
                # bulk conversion through the HOST row cache, then ONE
                # transfer to THIS shard's device only
                mx, my, mm = self._pk_rows_to_limbs(
                    [row for row, _ in misses], width,
                    row_keys=[key for _, key in misses])
                if st["check"] and self._wire_u16 and mx.size:
                    marshal.assert_canonical_limbs(mx, my)
                dmx = jax.device_put(mx, shard.device)
                dmy = jax.device_put(my, shard.device)
                dmm = jax.device_put(mm, shard.device)
                g2_bytes += mx.nbytes + my.nbytes + mm.nbytes
                miss_rows += len(misses)
                for j, (row, key) in enumerate(misses):
                    nbytes = mx[j].nbytes + my[j].nbytes + mm[j].nbytes
                    entry = (dmx[j], dmy[j], dmm[j], nbytes)
                    if key is not None:
                        self._mesh_shard_insert(
                            shard, (key, width, wire), entry)
                    miss_dev.append(entry)
            zx, zy, zm = self._mesh_zero_row(shard, width)
            xs, ys, ms = [], [], []
            for step in plan:
                if step[0] == "zero":
                    entry = (zx, zy, zm)
                elif step[0] == "hit":
                    entry = step[1]
                else:
                    entry = miss_dev[step[1]]
                xs.append(entry[0])
                ys.append(entry[1])
                ms.append(entry[2])
            # committed inputs -> the stack executes on the shard's
            # device; no cross-device bytes
            per_x.append(jnp.stack(xs))
            per_y.append(jnp.stack(ys))
            per_m.append(jnp.stack(ms))
        px = layout.assemble(per_x)
        py = layout.assemble(per_y)
        pm = layout.assemble(per_m)
        self._m_dev_hit.inc(hit_rows)
        self._m_dev_miss.inc(miss_rows)
        st["hit_rows"], st["hit_bytes"] = hit_rows, hit_bytes
        if batch_key is not None:
            nbytes = sum(int(a.nbytes) for a in (px, py, pm))
            with self._mesh_lock:
                self._mesh_memo = (batch_key, (px, py, pm),
                                   hit_bytes + g2_bytes, nbytes)
        return px, py, pm, g2_bytes

    def _mesh_zero_line(self, shard: MeshCacheShard):
        """Shard-local zero line table (the `_zero_line_row` contract,
        committed to the shard's device)."""
        import numpy as np

        with self._mesh_lock:
            row = shard.zero_rows.get("lines")
        if row is None:
            import jax

            row = (jax.device_put(
                       np.zeros(self._bn.LINE_TABLE_SHAPE, np.int32),
                       shard.device),
                   jax.device_put(np.asarray(True), shard.device))
            with self._mesh_lock:
                shard.zero_rows.setdefault("lines", row)
                row = shard.zero_rows["lines"]
        return row

    def _mesh_line_tables(self, st: dict, rows, keys, layout):
        """The mesh precomp path: resolve every batch row's line table
        against ITS device's cache shard, marshal + precompute misses
        on their owning device only (committed inputs keep the
        precompute dispatch device-local), stack per-device slabs and
        assemble the global sharded (B, L, 3, 2, nl) table + (B,)
        infinity flags with zero cross-device traffic. Returns
        (table, inf, transferred g2_bytes)."""
        import jax

        jnp = self._jnp
        width, bucket = st["width"], st["bucket"]
        rpd = layout.rows_per_device(bucket)
        if keys is not None and all(
                k is not None or not row for row, k in zip(rows, keys)):
            batch_key = (tuple(keys), bucket, "precomp",
                         layout.n_devices)
        else:
            batch_key = None
        st["line_key"] = batch_key
        with self._mesh_lock:
            memo = self._mesh_line_memo
        if batch_key is not None and memo is not None \
                and memo[0] == batch_key:
            tab, inf = memo[1]
            st["hit_rows"] = st["pk_rows"]
            st["hit_bytes"] = memo[2]
            self._m_dev_hit.inc(st["pk_rows"])
            return tab, inf, 0

        per_t, per_i = [], []
        g2_bytes = hit_rows = hit_bytes = miss_rows = 0
        for shard in self._mesh_shards:
            lo = shard.index * rpd
            s_rows = rows[lo:lo + rpd]
            s_keys = (keys[lo:lo + rpd] if keys is not None
                      else [None] * len(s_rows))
            plan = []  # ("zero",) | ("hit", entry) | ("miss", j)
            misses = []  # (row, key)
            with self._mesh_lock:
                for row, key in zip(s_rows, s_keys):
                    if not row:
                        plan.append(("zero",))
                        continue
                    entry = None
                    if key is not None:
                        entry = shard.cache.get((key, "lines"))
                        if entry is not None:
                            shard.cache.move_to_end((key, "lines"))
                    if entry is not None:
                        plan.append(("hit", entry))
                        hit_rows += 1
                        hit_bytes += entry[3]
                        shard.m_hit.inc()
                    else:
                        plan.append(("miss", len(misses)))
                        misses.append((row, key))
                        shard.m_miss.inc()
            miss_dev = []
            if misses:
                mx, my, mm = self._pk_rows_to_limbs(
                    [row for row, _ in misses], width,
                    row_keys=[key for _, key in misses])
                if st["check"] and self._wire_u16 and mx.size:
                    marshal.assert_canonical_limbs(mx, my)
                dmx = jax.device_put(mx, shard.device)
                dmy = jax.device_put(my, shard.device)
                dmm = jax.device_put(mm, shard.device)
                g2_bytes += mx.nbytes + my.nbytes + mm.nbytes
                miss_rows += len(misses)
                tabs, infs = self._precompute(dmx, dmy, dmm)
                for j, (row, key) in enumerate(misses):
                    nbytes = int(tabs[j].nbytes) + int(infs[j].nbytes)
                    entry = (tabs[j], infs[j], None, nbytes)
                    if key is not None:
                        self._mesh_shard_insert(
                            shard, (key, "lines"), entry)
                    miss_dev.append(entry)
            zt, zi = self._mesh_zero_line(shard)
            ts, fs = [], []
            for step in plan:
                if step[0] == "zero":
                    entry = (zt, zi)
                elif step[0] == "hit":
                    entry = step[1]
                else:
                    entry = miss_dev[step[1]]
                ts.append(entry[0])
                fs.append(entry[1])
            per_t.append(jnp.stack(ts))
            per_i.append(jnp.stack(fs))
        tab = layout.assemble(per_t)
        inf = layout.assemble(per_i)
        self._m_dev_hit.inc(hit_rows)
        self._m_dev_miss.inc(miss_rows)
        st["hit_rows"], st["hit_bytes"] = hit_rows, hit_bytes
        if batch_key is not None:
            nbytes = int(tab.nbytes) + int(inf.nbytes)
            with self._mesh_lock:
                self._mesh_line_memo = (batch_key, (tab, inf),
                                        hit_bytes + g2_bytes, nbytes)
        return tab, inf, g2_bytes
