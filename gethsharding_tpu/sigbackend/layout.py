"""Device placement: single device today, the shard mesh when asked.

One resolved `DeviceLayout` per backend instance decides WHERE every
plane lands. The default is the single-device layout the repo has run
since r0 (placement is `jnp.asarray`, the jit path untouched). With
``--mesh-devices``/``GETHSHARDING_MESH_DEVICES`` > 1 the layout builds
a 1-D ``("shard",)`` mesh over `parallel/mesh.make_mesh` and places
every batch plane as ``NamedSharding(P('shard'))`` along the leading
(shardID) axis — the SNIPPETS.md mesh idiom, and the same layout the
multi-chip dryrun proves bit-identical on the virtual CPU platform.

jax stays a lazy import throughout: resolving a single-device layout
must not initialize an accelerator backend (the CPU-only control-plane
contract of the package docstring).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from gethsharding_tpu.sigbackend.marshal import bucket_size

MESH_ENV = "GETHSHARDING_MESH_DEVICES"

# HLO op mnemonics that move bytes BETWEEN devices. Async pairs
# (`all-reduce-start`/`-done`) count once, on the start half.
_COLLECTIVE_OPS = ("all-reduce", "all-gather", "all-to-all",
                   "collective-permute", "reduce-scatter")


def mesh_devices_requested(explicit: Optional[int] = None) -> int:
    """The device count this process should lay out over: an explicit
    constructor argument wins, else ``GETHSHARDING_MESH_DEVICES``,
    else 1 (the single-device layout)."""
    if explicit is not None:
        return max(1, int(explicit))
    raw = os.environ.get(MESH_ENV, "").strip()
    return max(1, int(raw)) if raw else 1


def get_shard_map():
    """`shard_map` across jax versions: re-exported at top level on
    newer releases, under `jax.experimental` on 0.4.x."""
    try:
        from jax import shard_map  # type: ignore[attr-defined]
    except ImportError:  # pragma: no cover - version-dependent
        from jax.experimental.shard_map import shard_map
    return shard_map


def count_collectives(hlo_text: str) -> int:
    """Cross-device collective ops in a compiled HLO module — the
    transfer-ledger check behind the mesh audit's acceptance bar
    (exactly ONE vote-total all-reduce per step)."""
    n = 0
    for line in hlo_text.splitlines():
        stripped = line.strip()
        for op in _COLLECTIVE_OPS:
            if f" {op}(" in stripped or f" {op}-start(" in stripped:
                n += 1
                break
    return n


def count_ops(hlo_text: str, opcode: str) -> int:
    """Occurrences of one HLO opcode in a compiled module — the op
    census behind the precomp path's non-vacuity check: the warm
    precomp executable must carry far fewer `multiply` ops than the
    recompute executable, proving the fixed-argument Miller point
    arithmetic really is absent (same contract as `count_collectives`:
    counted from the optimized AOT text, no second compile)."""
    n = 0
    needle = f" {opcode}("
    for line in hlo_text.splitlines():
        if needle in line.strip():
            n += 1
    return n


class DeviceLayout:
    """Resolved placement for one backend instance.

    ``n_devices == 1``: no mesh, no sharding — `place` is a plain
    default-device transfer and the dispatch path is byte-identical to
    the pre-mesh backend. ``n_devices > 1``: a 1-D ``("shard",)`` mesh
    whose `NamedSharding` splits every leading batch axis into
    contiguous per-device slabs."""

    def __init__(self, n_devices: int = 1):
        self.n_devices = max(1, int(n_devices))
        self.mesh = None
        self.sharding = None
        self.devices: Sequence = ()
        if self.n_devices > 1:
            # lazy: only a mesh layout touches jax (and so the backend)
            from gethsharding_tpu.parallel.mesh import (
                make_mesh, shard_axis_sharding)

            self.mesh = make_mesh(self.n_devices)
            self.sharding = shard_axis_sharding(self.mesh)
            self.devices = list(self.mesh.devices.flat)

    @property
    def is_mesh(self) -> bool:
        return self.mesh is not None

    def shard_spec(self):
        """PartitionSpec splitting the leading axis over every mesh
        axis (the in/out spec of the one-step mesh audit)."""
        from jax.sharding import PartitionSpec

        return PartitionSpec(tuple(self.mesh.axis_names))

    def mesh_bucket(self, n: int) -> int:
        """The mesh batch bucket: `bucket_size`, then rounded up to a
        multiple of the device count so the `NamedSharding` split is
        even (XLA shards contiguous equal slabs; padded rows are masked
        rejections exactly like single-device padding)."""
        bucket = bucket_size(n)
        d = self.n_devices
        return -(-bucket // d) * d

    def rows_per_device(self, bucket: int) -> int:
        return bucket // self.n_devices

    def device_of_row(self, row: int, bucket: int) -> int:
        """Which mesh slot a (padded) batch row lands on under the
        contiguous leading-axis split — the cache sharding function."""
        return min(row // self.rows_per_device(bucket),
                   self.n_devices - 1)

    def place(self, host_array):
        """Ship one host plane: split along the leading axis over the
        mesh (each device receives only its slab's bytes)."""
        import jax

        return jax.device_put(host_array, self.sharding)

    def assemble(self, per_device: Sequence):
        """One global sharded array from per-device slabs already
        resident on their devices — `make_array_from_single_device_
        arrays`, ZERO bytes crossing the host->device link or the
        interconnect (the mesh half of the residency claim)."""
        import jax

        first = per_device[0]
        shape = (first.shape[0] * self.n_devices,) + tuple(first.shape[1:])
        return jax.make_array_from_single_device_arrays(
            shape, self.sharding, list(per_device))
