"""Wire encoding for the mainchain RPC surface.

JSON-RPC 2.0 payload values: addresses/hashes/byte strings as 0x-hex,
bn256 curve points as hex-int coordinate arrays (G1 = [x, y], G2 =
[[xa, xb], [ya, yb]], null = infinity/absent), registry entries and
collation records as plain objects. Deliberately schema-first and
version-tagged so a non-Python peer can implement the same surface.
"""

from __future__ import annotations

from typing import Optional

from gethsharding_tpu.crypto import bn256
from gethsharding_tpu.utils.hexbytes import Address20, Hash32


def enc_bytes(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def dec_bytes(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


def enc_g1(p: Optional[bn256.G1Point]) -> Optional[list]:
    return None if p is None else [hex(p[0]), hex(p[1])]


def dec_g1(v) -> Optional[bn256.G1Point]:
    return None if v is None else (int(v[0], 16), int(v[1], 16))


def enc_g2(p: Optional[bn256.G2Point]) -> Optional[list]:
    if p is None:
        return None
    x, y = p
    return [[hex(x.a), hex(x.b)], [hex(y.a), hex(y.b)]]


def dec_g2(v) -> Optional[bn256.G2Point]:
    if v is None:
        return None
    (xa, xb), (ya, yb) = v
    return (bn256.Fp2(int(xa, 16), int(xb, 16)),
            bn256.Fp2(int(ya, 16), int(yb, 16)))


def enc_registry(entry) -> Optional[dict]:
    if entry is None:
        return None
    return {
        "deregisteredPeriod": entry.deregistered_period,
        "poolIndex": entry.pool_index,
        "balance": entry.balance,
        "deposited": entry.deposited,
        "blsPubkey": enc_g2(entry.bls_pubkey),
        "blsPop": enc_g1(entry.bls_pop),
    }


def dec_registry(obj: Optional[dict]):
    if obj is None:
        return None
    from gethsharding_tpu.smc.state_machine import Notary

    return Notary(
        deregistered_period=obj["deregisteredPeriod"],
        pool_index=obj["poolIndex"],
        balance=obj["balance"],
        deposited=obj["deposited"],
        bls_pubkey=dec_g2(obj["blsPubkey"]),
        bls_pop=dec_g1(obj["blsPop"]),
    )


def enc_record(record) -> Optional[dict]:
    if record is None:
        return None
    return {
        "chunkRoot": enc_bytes(record.chunk_root),
        "proposer": enc_bytes(record.proposer),
        "isElected": record.is_elected,
        "signature": enc_bytes(record.signature),
        "voteSigs": {str(i): [enc_g1(v.sig), enc_bytes(v.signer)]
                     for i, v in record.vote_sigs.items()},
        "voteCount": record.vote_count,
    }


def dec_record(obj: Optional[dict]):
    if obj is None:
        return None
    from gethsharding_tpu.smc.state_machine import CollationRecord, VoteSig

    return CollationRecord(
        chunk_root=Hash32(dec_bytes(obj["chunkRoot"])),
        proposer=Address20(dec_bytes(obj["proposer"])),
        is_elected=obj["isElected"],
        signature=dec_bytes(obj["signature"]),
        vote_sigs={int(i): VoteSig(sig=dec_g1(v[0]),
                                   signer=Address20(dec_bytes(v[1])))
                   for i, v in obj["voteSigs"].items()},
        vote_count=obj["voteCount"],
    )


# -- verification plane codecs (the serving RPC surface) -------------------
# The committee and DAS planes ship RAGGED batches: per-row committee
# signature/pubkey point lists and per-row merkle sibling paths. The
# wire forms are plain nested JSON of the scalar codecs above, so a
# frontend router can balance EVERY SigBackend op cross-process with
# the same schema-first contract as the rest of the surface.


def enc_g1_rows(rows) -> list:
    """Per-row G1 point lists (committee vote signatures)."""
    return [[enc_g1(p) for p in row] for row in rows]


def dec_g1_rows(rows) -> list:
    return [[dec_g1(p) for p in row] for row in rows]


def enc_g2_rows(rows) -> list:
    """Per-row G2 point lists (committee member pubkeys)."""
    return [[enc_g2(p) for p in row] for row in rows]


def dec_g2_rows(rows) -> list:
    return [[dec_g2(p) for p in row] for row in rows]


def enc_pk_row_keys(keys) -> Optional[list]:
    """Optional per-row pk-plane cache keys. Keys are arbitrary
    hashables caller-side (the notary uses int tuples); the wire form
    is their `repr` — injective for the int/str/tuple keys in use, so
    the remote backend's cache key still uniquely determines the row's
    points, and stable across processes (unlike `hash`, repr does not
    depend on PYTHONHASHSEED)."""
    if keys is None:
        return None
    return [None if k is None else repr(k) for k in keys]


def enc_das_call(chunks, indices, proofs, roots) -> list:
    """The das_verify_samples argument plane: (chunks, indices,
    sibling-path rows, roots) — positional, matching the backend op."""
    return [
        [enc_bytes(c) for c in chunks],
        [int(i) for i in indices],
        [[enc_bytes(node) for node in path] for path in proofs],
        [enc_bytes(r) for r in roots],
    ]


def dec_das_call(chunks, indices, proofs, roots) -> tuple:
    return (
        [dec_bytes(c) for c in chunks],
        [int(i) for i in indices],
        [[dec_bytes(node) for node in path] for path in proofs],
        [dec_bytes(r) for r in roots],
    )


def enc_das_poly_call(commitments, index_rows, eval_rows, proofs,
                      ns) -> list:
    """The das_verify_multiproofs argument plane: (64-byte G1
    commitments, per-row sampled index sets, per-row claimed
    evaluations as hex field elements, 64-byte G1 multiproofs, domain
    sizes) — positional, matching the backend op."""
    return [
        [enc_bytes(c) for c in commitments],
        [[int(i) for i in row] for row in index_rows],
        [[hex(int(e)) for e in row] for row in eval_rows],
        [enc_bytes(p) for p in proofs],
        [int(n) for n in ns],
    ]


def dec_das_poly_call(commitments, index_rows, eval_rows, proofs,
                      ns) -> tuple:
    return (
        [dec_bytes(c) for c in commitments],
        [[int(i) for i in row] for row in index_rows],
        [[int(e, 16) for e in row] for row in eval_rows],
        [dec_bytes(p) for p in proofs],
        [int(n) for n in ns],
    )


# -- fleettrace span-batch codec (the shard_traceExport plane) -------------
# Finished tracer records travel as compact positional rows, not
# keyed objects: an export batch is the highest-volume payload on the
# control plane (hundreds of spans per flush) and the field names
# would dominate the wire bytes. `dur_us` is derived, so it is NOT
# shipped — the decoder recomputes it.

_JSON_SCALARS = (str, int, float, bool, type(None))

# The trace plane is invisible to tracing. A client span around
# `shard_traceExport` lands in the very export buffer the call is
# shipping — the drain can never go empty (a self-sustaining feedback
# loop) — and a handler span per batch floods the collector with
# meta-traces of its own transport; exemplar polls would evict the
# exemplars they read. Client and server both skip span creation for
# these methods.
TRACE_PLANE_METHODS = frozenset({
    "shard_traceExport", "shard_traceHandshake",
    "shard_traceAttribution", "shard_traceExemplars"})


def enc_span_tags(tags) -> Optional[dict]:
    """Span tags with non-JSON values coerced to repr: tags are an
    open dict (callers stash whatever helps debugging) and one exotic
    value must not poison a whole export batch at serialization time."""
    if not tags:
        return None
    return {str(k): (v if isinstance(v, _JSON_SCALARS) else repr(v))
            for k, v in tags.items()}


def enc_spans(records) -> list:
    """Tracer records -> positional rows
    ``[name, trace, span, parent, start, end, tid, tags]`` (monotonic
    seconds; the batch envelope carries the producer's clock anchor)."""
    return [[r["name"], r["trace"], r["span"], r["parent"],
             r["start"], r["end"], r["tid"], enc_span_tags(r["tags"])]
            for r in records]


def dec_spans(rows) -> list:
    out = []
    for name, trace, span, parent, start, end, tid, tags in rows:
        start = float(start)
        end = float(end)
        out.append({
            "name": str(name), "trace": int(trace), "span": int(span),
            "parent": None if parent is None else int(parent),
            "start": start, "end": end,
            "dur_us": round((end - start) * 1e6, 1),
            "tid": None if tid is None else int(tid),
            "tags": dict(tags) if tags else {},
        })
    return out


# -- shardp2p message codecs (type-tagged, for the cross-process relay) ----


def enc_p2p(data) -> tuple:
    """Message object -> (type tag, JSON payload)."""
    from gethsharding_tpu.p2p import messages as m

    if isinstance(data, m.CollationBodyRequest):
        return "CollationBodyRequest", {
            "chunkRoot": None if data.chunk_root is None
            else enc_bytes(data.chunk_root),
            "shardId": data.shard_id,
            "period": data.period,
            "proposer": None if data.proposer is None
            else enc_bytes(data.proposer),
            "signature": enc_bytes(data.signature),
        }
    if isinstance(data, m.CollationBodyResponse):
        return "CollationBodyResponse", {
            "headerHash": enc_bytes(data.header_hash),
            "body": enc_bytes(data.body),
        }
    if isinstance(data, m.ChunkProofRequest):
        return "ChunkProofRequest", {
            "chunkRoot": enc_bytes(data.chunk_root),
            "shardId": data.shard_id,
            "period": data.period,
            "index": data.index,
        }
    if isinstance(data, m.ChunkProofResponse):
        return "ChunkProofResponse", {
            "chunkRoot": enc_bytes(data.chunk_root),
            "index": data.index,
            "proof": [enc_bytes(node) for node in data.proof],
            "bodyLen": data.body_len,
        }
    if isinstance(data, m.DASCommitmentRequest):
        return "DASCommitmentRequest", {
            "shardId": data.shard_id,
            "period": data.period,
        }
    if isinstance(data, m.DASCommitmentResponse):
        return "DASCommitmentResponse", {
            "shardId": data.shard_id,
            "period": data.period,
            "chunkRoot": enc_bytes(data.chunk_root),
            "dasRoot": enc_bytes(data.das_root),
            "k": data.k,
            "n": data.n,
            "bodyLen": data.body_len,
            "polyCommitment": enc_bytes(data.poly_commitment),
            "signature": enc_bytes(data.signature),
        }
    if isinstance(data, m.DASampleRequest):
        return "DASampleRequest", {
            "dasRoot": enc_bytes(data.das_root),
            "indices": list(data.indices),
        }
    if isinstance(data, m.DASampleResponse):
        return "DASampleResponse", {
            "dasRoot": enc_bytes(data.das_root),
            "index": data.index,
            "chunk": enc_bytes(data.chunk),
            "proof": [enc_bytes(node) for node in data.proof],
        }
    if isinstance(data, m.DASMultiproofRequest):
        return "DASMultiproofRequest", {
            "dasRoot": enc_bytes(data.das_root),
            "indices": list(data.indices),
        }
    if isinstance(data, m.DASMultiproofResponse):
        return "DASMultiproofResponse", {
            "dasRoot": enc_bytes(data.das_root),
            "indices": list(data.indices),
            "chunks": [enc_bytes(c) for c in data.chunks],
            "proof": enc_bytes(data.proof),
        }
    from gethsharding_tpu.p2p.whisper import Envelope

    if isinstance(data, Envelope):
        return "WhisperEnvelope", {
            "expiry": data.expiry,
            "ttl": data.ttl,
            "topic": enc_bytes(data.topic),
            "ciphertext": enc_bytes(data.ciphertext),
            "nonce": data.nonce,
        }
    from gethsharding_tpu.p2p import discovery as disc

    if isinstance(data, disc.PeerTableRequest):
        return "PeerTableRequest", {}
    if isinstance(data, disc.PeerTableResponse):
        return "PeerTableResponse", {
            "announces": [_enc_announce(a) for a in data.announces],
        }
    from gethsharding_tpu.storage import netstore as ns

    if isinstance(data, ns.ChunkRequest):
        return "ChunkRequest", {"key": enc_bytes(data.key)}
    if isinstance(data, ns.ChunkDelivery):
        return "ChunkDelivery", {"key": enc_bytes(data.key),
                                 "span": data.span,
                                 "payload": enc_bytes(data.payload)}
    raise TypeError(f"no p2p wire codec for {type(data).__name__}")


def _enc_announce(ann) -> dict:
    return {"peerId": ann.peer_id, "account": ann.account,
            "host": ann.host, "port": ann.port, "seq": ann.seq,
            "sig": enc_bytes(ann.sig)}


def _dec_announce(obj: dict):
    from gethsharding_tpu.p2p import discovery as disc

    return disc.PeerAnnounce(
        peer_id=int(obj["peerId"]), account=str(obj["account"]),
        host=str(obj["host"]), port=int(obj["port"]), seq=int(obj["seq"]),
        sig=dec_bytes(obj["sig"]))


def dec_p2p(kind: str, payload: dict):
    from gethsharding_tpu.p2p import messages as m

    if kind == "CollationBodyRequest":
        return m.CollationBodyRequest(
            chunk_root=None if payload["chunkRoot"] is None
            else Hash32(dec_bytes(payload["chunkRoot"])),
            shard_id=payload["shardId"],
            period=payload["period"],
            proposer=None if payload["proposer"] is None
            else Address20(dec_bytes(payload["proposer"])),
            signature=dec_bytes(payload["signature"]),
        )
    if kind == "CollationBodyResponse":
        return m.CollationBodyResponse(
            header_hash=Hash32(dec_bytes(payload["headerHash"])),
            body=dec_bytes(payload["body"]),
        )
    if kind == "ChunkProofRequest":
        return m.ChunkProofRequest(
            chunk_root=Hash32(dec_bytes(payload["chunkRoot"])),
            shard_id=payload["shardId"],
            period=payload["period"],
            index=payload["index"],
        )
    if kind == "ChunkProofResponse":
        return m.ChunkProofResponse(
            chunk_root=Hash32(dec_bytes(payload["chunkRoot"])),
            index=payload["index"],
            proof=tuple(dec_bytes(node) for node in payload["proof"]),
            body_len=payload.get("bodyLen", 0),
        )
    if kind == "DASCommitmentRequest":
        return m.DASCommitmentRequest(
            shard_id=int(payload["shardId"]),
            period=int(payload["period"]),
        )
    if kind == "DASCommitmentResponse":
        return m.DASCommitmentResponse(
            shard_id=int(payload["shardId"]),
            period=int(payload["period"]),
            chunk_root=Hash32(dec_bytes(payload["chunkRoot"])),
            das_root=dec_bytes(payload["dasRoot"]),
            k=int(payload["k"]),
            n=int(payload["n"]),
            body_len=int(payload["bodyLen"]),
            poly_commitment=dec_bytes(payload.get("polyCommitment", "")),
            signature=dec_bytes(payload["signature"]),
        )
    if kind == "DASampleRequest":
        return m.DASampleRequest(
            das_root=dec_bytes(payload["dasRoot"]),
            indices=tuple(int(i) for i in payload["indices"]),
        )
    if kind == "DASampleResponse":
        return m.DASampleResponse(
            das_root=dec_bytes(payload["dasRoot"]),
            index=int(payload["index"]),
            chunk=dec_bytes(payload["chunk"]),
            proof=tuple(dec_bytes(node) for node in payload["proof"]),
        )
    if kind == "DASMultiproofRequest":
        return m.DASMultiproofRequest(
            das_root=dec_bytes(payload["dasRoot"]),
            indices=tuple(int(i) for i in payload["indices"]),
        )
    if kind == "DASMultiproofResponse":
        return m.DASMultiproofResponse(
            das_root=dec_bytes(payload["dasRoot"]),
            indices=tuple(int(i) for i in payload["indices"]),
            chunks=tuple(dec_bytes(c) for c in payload["chunks"]),
            proof=dec_bytes(payload["proof"]),
        )
    if kind == "WhisperEnvelope":
        from gethsharding_tpu.p2p.whisper import Envelope

        # coerce the int fields: a peer-supplied non-int would otherwise
        # detonate later inside the whisper daemon thread, not here at
        # the wire boundary where the caller's guard catches it
        return Envelope(
            expiry=int(payload["expiry"]),
            ttl=int(payload["ttl"]),
            topic=dec_bytes(payload["topic"]),
            ciphertext=dec_bytes(payload["ciphertext"]),
            nonce=int(payload["nonce"]),
        )
    if kind == "ChunkRequest":
        from gethsharding_tpu.storage import netstore as ns

        return ns.ChunkRequest(key=dec_bytes(payload["key"]))
    if kind == "ChunkDelivery":
        from gethsharding_tpu.storage import netstore as ns

        return ns.ChunkDelivery(key=dec_bytes(payload["key"]),
                                span=int(payload["span"]),
                                payload=dec_bytes(payload["payload"]))
    if kind == "PeerTableRequest":
        from gethsharding_tpu.p2p import discovery as disc

        return disc.PeerTableRequest()
    if kind == "PeerTableResponse":
        from gethsharding_tpu.p2p import discovery as disc

        return disc.PeerTableResponse(
            announces=tuple(_dec_announce(a)
                            for a in payload.get("announces", [])))
    raise ValueError(f"unknown p2p message type {kind!r}")


def enc_block(block) -> dict:
    return {"number": block.number, "hash": enc_bytes(block.hash),
            "parentHash": enc_bytes(block.parent_hash),
            "extra": enc_bytes(getattr(block, "extra", b"") or b"")}


def dec_block(obj: dict):
    from gethsharding_tpu.smc.chain import Block

    return Block(number=int(obj["number"]),
                 hash=Hash32(dec_bytes(obj["hash"])),
                 parent_hash=Hash32(dec_bytes(obj["parentHash"])),
                 extra=dec_bytes(obj.get("extra", "")))


def enc_receipt(receipt) -> dict:
    return {"txHash": enc_bytes(receipt.tx_hash), "status": receipt.status,
            "blockNumber": receipt.block_number}
