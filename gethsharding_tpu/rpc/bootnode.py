"""Standalone introduction node: `python -m gethsharding_tpu.rpc.bootnode`.

The `cmd/bootnode` analog: the reference ships a stripped node that runs
ONLY the discovery/bootstrap layer so peers can find each other without
a full chain node. Here the introduction tier is the shardp2p relay
(authenticated attach, peer table with listener endpoints, broadcast
fan-out — `rpc/server.py` shard_p2p*), so a bootnode is an RPCServer
over a chainless stub backend: it refuses every chain/SMC method but
serves the full peer-introduction surface, and the direct
(`p2p/direct.py`) data plane works unchanged — payloads never transit
the bootnode, exactly as they never transit `cmd/bootnode`.

Prints one JSON line {"host": ..., "port": ...} once listening.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time

from gethsharding_tpu.params import Config


class _IntroductionOnly:
    """Backend stub: network identity, no chain. Any chain/SMC read or
    transaction fails loudly — a bootnode introduces peers, nothing
    else (cmd/bootnode serves discovery only)."""

    def __init__(self, config: Config):
        self.config = config

    def subscribe_new_head(self, callback):
        return lambda: None  # no chain: no heads ever

    def __getattr__(self, name):
        raise AttributeError(
            f"bootnode serves peer introduction only; {name!r} needs a "
            f"chain process (rpc/chain_server.py)")


def make_bootnode(host: str = "127.0.0.1", port: int = 0,
                  network_id: int = None):
    """An RPCServer serving only the shardp2p introduction surface."""
    from gethsharding_tpu.rpc.server import RPCServer

    config = Config() if network_id is None else Config(
        network_id=network_id)
    return RPCServer(_IntroductionOnly(config), host=host, port=port)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="bootnode")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--networkid", type=int, default=None)
    parser.add_argument("--runtime", type=float, default=0.0,
                        help="seconds before exit (0 = forever)")
    parser.add_argument("--verbosity", default="warning")
    args = parser.parse_args(argv)

    logging.basicConfig(level=getattr(logging, args.verbosity.upper()))
    server = make_bootnode(args.host, args.port, args.networkid)
    server.start()
    host, port = server.address
    print(json.dumps({"host": host, "port": port}), flush=True)
    deadline = time.monotonic() + args.runtime if args.runtime else None
    try:
        while deadline is None or time.monotonic() < deadline:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
