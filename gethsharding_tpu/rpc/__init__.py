"""JSON-RPC transport: the actor <-> mainchain process boundary.

Parity: `rpc/` (server `rpc/server.go:46`, IPC codec, subscriptions) and
`sharding/mainchain/utils.go:17-22` (dialRPC) — the reference's actors
talk to a separate geth process over newline-delimited JSON-RPC on an IPC
socket. Here the same wire protocol runs over TCP (or a unix socket):
`RPCServer` exposes a SimulatedMainchain, `RemoteMainchain` is the
client-side backend an `SMCClient` can use in place of the in-process
chain, making the sharding node a genuinely separate OS process.
"""

from gethsharding_tpu.rpc.client import RemoteMainchain, RPCClient, RPCError
from gethsharding_tpu.rpc.server import RPCServer

__all__ = ["RPCClient", "RPCError", "RPCServer", "RemoteMainchain"]
