"""Standalone mainchain process: `python -m gethsharding_tpu.rpc.chain_server`.

The dev-mode equivalent of the geth process the reference's actors dial
(`sharding/mainchain/utils.go:17` — one mainchain node, N actor
processes). Hosts a SimulatedMainchain behind an RPCServer; block
production is either timed (--blocktime) or driven remotely via the
shard_commit / shard_fastForward dev methods.

Prints one JSON line {"host": ..., "port": ...} on stdout once listening,
so a parent process (test harness, orchestrator) can dial it.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

from gethsharding_tpu.params import Config
from gethsharding_tpu.rpc.server import RPCServer
from gethsharding_tpu.smc.chain import SimulatedMainchain


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="chain-server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--periodlength", type=int, default=5)
    parser.add_argument("--quorum", type=int, default=None,
                        help="override QUORUM_SIZE (dev/test chains)")
    parser.add_argument("--shardcount", type=int, default=None)
    parser.add_argument("--networkid", type=int, default=None)
    parser.add_argument("--blocktime", type=float, default=0.0,
                        help="auto block production interval (0 = manual "
                             "via shard_commit / shard_fastForward)")
    parser.add_argument("--runtime", type=float, default=0.0,
                        help="seconds before exit (0 = forever)")
    parser.add_argument("--follow", default=None, metavar="HOST:PORT",
                        help="run as a FOLLOWER replicating the leader "
                             "chain process at HOST:PORT (headers "
                             "engine-verified, state via checkpoint "
                             "pull — smc/sync.py)")
    parser.add_argument("--sigbackend", default="python",
                        choices=("python", "jax", "failover-python",
                                 "failover-jax"),
                        help="backend behind the shard_ecrecover / "
                             "shard_verifyAggregates serving tier: handler "
                             "threads coalesce concurrent requests into "
                             "shared dispatches (jax = batched TPU "
                             "kernels); failover-* composes the serving "
                             "tier behind a circuit breaker over the "
                             "scalar fallback, and exports the breaker "
                             "state on shard_health so a fleet router "
                             "(gethsharding_tpu/fleet/) drains a tripped "
                             "replica")
    parser.add_argument("--mesh-devices", type=int, default=None,
                        help="lay the jax sigbackend over an N-device "
                             "1-D shard mesh (sets GETHSHARDING_MESH_"
                             "DEVICES before the backend is built; "
                             "1 = single device, the default)")
    parser.add_argument("--serving-watchdog-s", type=float, default=0.0,
                        help="dispatch watchdog deadline for the serving "
                             "tier (0 = off): a wedged device call fails "
                             "its batch with DeadlineExceeded — under "
                             "failover-* that is a breaker fault, and a "
                             "router retries the caller on the next "
                             "replica")
    parser.add_argument("--serving-quota-rows", type=int, default=None,
                        help="per-tenant queued-row quota in the serving "
                             "admission queues (default: "
                             "GETHSHARDING_TENANT_QUOTA_ROWS, 0 = off)")
    parser.add_argument("--chaos", default="", metavar="SPEC",
                        help="seeded chaos schedule at the backend/"
                             "dispatch seams (resilience/chaos.py) — the "
                             "router smoke trips one replica's breaker "
                             "with this")
    parser.add_argument("--soundness-rate", type=float, default=None,
                        help="continuous soundness spot-check rate for "
                             "this replica's serving planes (resilience/"
                             "soundness.py; default GETHSHARDING_"
                             "SOUNDNESS_RATE, 0 = off) — pair with "
                             "--sigbackend failover-* so a detected "
                             "silent corruption trips the breaker and "
                             "a fleet frontend drains the replica")
    parser.add_argument("--trace", action="store_true",
                        help="collect RPC-handler + serving-tier spans "
                             "(per-request queue/assembly/dispatch "
                             "attribution) in the in-memory tracer")
    parser.add_argument("--trace-out", default="",
                        help="write collected spans as Chrome trace_event "
                             "JSON at exit (Perfetto); implies --trace")
    parser.add_argument("--trace-ring", type=int, default=4096,
                        help="finished-span ring capacity")
    parser.add_argument("--fleettrace-export", default=None,
                        metavar="HOST:PORT",
                        help="ship finished spans to the fleettrace "
                             "collector at HOST:PORT (a fleet frontend "
                             "run with --fleettrace) so this replica's "
                             "spans join the cross-process trace trees; "
                             "implies --trace (default: GETHSHARDING_"
                             "FLEETTRACE_EXPORT)")
    parser.add_argument("--verbosity", default="warning")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=getattr(logging, args.verbosity.upper()),
        format="%(asctime)s %(levelname)-7s %(name)s "
               "[%(trace_id)s]  %(message)s",
        datefmt="%H:%M:%S")
    # log <-> trace correlation (same stamp as the sharding CLI): a
    # replica's warnings join against its /trace + RPC-stitched spans
    from gethsharding_tpu import tracing

    tracing.install_log_correlation()
    fleettrace_export = args.fleettrace_export
    if fleettrace_export is None:
        fleettrace_export = os.environ.get(
            "GETHSHARDING_FLEETTRACE_EXPORT") or None
    if args.trace or args.trace_out or fleettrace_export:
        tracing.enable(ring_spans=args.trace_ring)
    overrides = {"period_length": args.periodlength}
    if args.quorum is not None:
        overrides["quorum_size"] = args.quorum
    if args.shardcount is not None:
        overrides["shard_count"] = args.shardcount
    if args.networkid is not None:
        overrides["network_id"] = args.networkid
    config = Config(**overrides)
    backend = SimulatedMainchain(config=config)
    # the serving seam: verification RPCs coalesce across handler
    # threads onto the chosen backend. A replica composes explicitly —
    # device → (chaos) → serving → (failover) — so shard_health exports
    # the breaker state and a fleet router can drain a tripped replica;
    # the plain names keep the old lazy-wrap behavior.
    from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
    from gethsharding_tpu.sigbackend import get_backend

    if args.mesh_devices is not None:
        # the jax factory reads the env var at build time, so the flag
        # must land before the first get_backend("jax") in this process
        os.environ["GETHSHARDING_MESH_DEVICES"] = str(args.mesh_devices)
    failover = args.sigbackend.startswith("failover-")
    inner_name = (args.sigbackend[len("failover-"):] if failover
                  else args.sigbackend)
    sig_backend = get_backend(inner_name)
    if args.chaos:
        from gethsharding_tpu.resilience.chaos import (ChaosSigBackend,
                                                       parse_spec)

        sig_backend = ChaosSigBackend(sig_backend, parse_spec(args.chaos))
    sig_backend = ServingSigBackend(sig_backend, ServingConfig(
        watchdog_s=args.serving_watchdog_s,
        tenant_quota_rows=args.serving_quota_rows))
    composed = sig_backend
    # the node CLI's composition order (node/backend.py): device →
    # chaos → serving → soundness → failover, so a detected silent
    # corruption is a primary fault the breaker (and through
    # shard_health, a fleet frontend) acts on
    soundness_rate = args.soundness_rate
    if soundness_rate is None:
        soundness_rate = float(
            os.environ.get("GETHSHARDING_SOUNDNESS_RATE", "0") or 0)
    if soundness_rate > 0:
        from gethsharding_tpu.resilience.soundness import (
            SpotCheckSigBackend)

        if not failover:
            logging.getLogger("chain-server").warning(
                "--soundness-rate without --sigbackend failover-*: a "
                "detected corruption raises to the caller instead of "
                "tripping a breaker")
        sig_backend = SpotCheckSigBackend(sig_backend,
                                          rate=soundness_rate)
    if failover:
        from gethsharding_tpu.resilience.breaker import FailoverSigBackend

        sig_backend = FailoverSigBackend(sig_backend,
                                         get_backend("python"))
    # boot the SLO tracker so this replica's shard_metrics snapshot
    # carries the slo/<class>/... series from the first federation
    # scrape (env-derived objectives; serving records the events)
    from gethsharding_tpu import slo

    slo.tracker()
    # device introspection plane: HBM poller + the devscope/* rows this
    # replica's shard_metrics snapshot federates; shard_profileStart /
    # shard_profileStop toggle on-demand profiling over the RPC below
    from gethsharding_tpu import devscope

    devscope.boot()
    # fleettrace export plane: a background exporter drains this
    # replica's finished spans to the fleet frontend's collector, which
    # rebases them onto the frontend clock (handshake-measured skew)
    # and assembles the cross-process trace trees
    if fleettrace_export:
        from gethsharding_tpu import fleettrace

        fleettrace.boot_exporter(fleettrace_export,
                                 label="chain-%d" % os.getpid())
    server = RPCServer(backend, host=args.host, port=args.port,
                       sig_backend=sig_backend)
    server.start()
    follower = None
    if args.follow:
        from gethsharding_tpu.smc.sync import ChainFollower

        leader_host, leader_port = args.follow.rsplit(":", 1)
        follower = ChainFollower(backend, leader_host, int(leader_port))
        follower.start()
    print(json.dumps({"host": server.address[0], "port": server.address[1]}),
          flush=True)

    deadline = time.monotonic() + args.runtime if args.runtime else None
    try:
        while deadline is None or time.monotonic() < deadline:
            if args.blocktime > 0 and follower is None:
                time.sleep(args.blocktime)
                backend.commit()
            else:
                time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        if follower is not None:
            follower.stop()
        server.stop()
        if fleettrace_export:
            from gethsharding_tpu import fleettrace

            fleettrace.shutdown()
        devscope.shutdown()
        # the server never owned the injected composition: drain-and-
        # fail its queued serving futures here so no caller is stranded
        composed.close()
        if args.trace_out:
            from gethsharding_tpu import tracing

            try:
                tracing.write_chrome_trace(args.trace_out)
            except OSError:
                logging.getLogger("chain-server").warning(
                    "trace export to %s failed", args.trace_out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
