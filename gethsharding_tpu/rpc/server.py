"""RPCServer: expose a SimulatedMainchain over JSON-RPC 2.0.

Parity: `rpc/server.go:46` + the IPC codec (`rpc/ipc.go`,
`rpc/json.go`) — newline-delimited JSON-RPC 2.0 frames over a stream
socket, one goroutine-equivalent thread per connection, `shard_subscribe`
push notifications for new heads (the `eth_subscribe` pattern the notary's
head loop depends on, `sharding/notary/notary.go:33-38`).

SMC reverts map to JSON-RPC error code 3 (geth's revert error code) with
the revert reason in `message`; the client re-raises them as `SMCRevert`
so actor-side control flow is identical in- and cross-process.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import socketserver
import threading
import time
from typing import Optional

from gethsharding_tpu import tracing
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.p2p.service import (
    PROTOCOL_NAME as P2P_PROTOCOL_NAME,
    PROTOCOL_VERSION as P2P_PROTOCOL_VERSION,
)
from gethsharding_tpu.smc.chain import SimulatedMainchain
from gethsharding_tpu.smc.state_machine import SMCRevert
from gethsharding_tpu.utils.hexbytes import Address20, Hash32

log = logging.getLogger("rpc.server")

REVERT_CODE = 3
METHOD_NOT_FOUND = -32601
INVALID_REQUEST = -32600
INTERNAL_ERROR = -32603

# per-connection dispatch concurrency: one socket carries MANY
# multiplexed requests (a fleet frontend funnels every routed call for
# a replica over ONE RPCClient), so handling them serially in the read
# loop would cap a replica at one in-flight request per upstream and
# starve the serving tier's coalescing + queue-depth signal. Each
# request dispatches on its own worker; the bound makes the read loop
# itself the backpressure once a connection has this many in flight.
CONN_CONCURRENCY = int(os.environ.get(
    "GETHSHARDING_RPC_CONN_CONCURRENCY", "64"))


class RPCServer:
    """Threaded JSON-RPC server over TCP (host, port) — port 0 picks a
    free one (`server.address` reports the bound endpoint)."""

    def __init__(self, backend: SimulatedMainchain,
                 host: str = "127.0.0.1", port: int = 0,
                 sig_backend=None, das=None):
        self.backend = backend
        # data-availability sampling provider (a das.service.DASService,
        # or anything with get_sample/da_status): backs the light-client
        # sample surface `shard_getSample` / `shard_daStatus`. None =
        # this process holds no blobs; the methods answer "unknown".
        self._das = das
        self._subscribers: dict = {}  # wfile -> (lock, peer id)
        self._sub_lock = threading.Lock()
        # verification serving seam: handler threads SUBMIT signature
        # work to the coalescing tier instead of driving a backend
        # inline, so concurrent RPC clients share device dispatches
        # (gethsharding_tpu/serving/). Built lazily on first use when
        # not injected — chain processes that never verify pay nothing.
        self._sig_backend = sig_backend
        self._sig_serving = None
        self._sig_serving_owned = False
        # fleet drain lifecycle: a DRAINING server refuses NEW
        # verification work with a typed "replica draining" error (the
        # router retries on the next replica) while in-flight requests
        # finish; `shard_health` exports the flag plus the breaker /
        # serving state the router's health sweep reads
        self.draining = False
        self._inflight = 0
        server = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                server._handle_connection(self)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = Server((host, port), Handler)
        self.address = self._tcp.server_address  # (host, bound_port)
        self._thread: Optional[threading.Thread] = None
        self._unsubscribe = backend.subscribe_new_head(self._on_head)
        # shardp2p relay: peer id -> (wfile, write lock); actors in other
        # processes attach here for introduction (authenticated peer
        # table + broadcast); directed payloads flow peer-to-peer over
        # the listeners the peers advertise (p2p/direct.py)
        self._p2p_peers: dict = {}
        self._p2p_meta: dict = {}
        self._p2p_ids = 1
        self._p2p_challenges: dict = {}  # wfile -> pending nonce
        self.p2p_relayed_sends = 0  # directed sends that fell back to us
        self.method_calls: dict = {}  # per-method request counts

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, daemon=True, name="rpc-server")
        self._thread.start()
        log.info("RPC listening on %s:%d", *self.address)

    def stop(self, grace_s: float = 5.0) -> None:
        """Graceful shutdown: stop admitting NEW verification work,
        give in-flight RPC requests a bounded grace to finish, then
        close the serving tier — whose `PipelinedDispatcher.close(
        wait=True)` semantics drain what it can and FAIL the rest with
        `DispatcherClosed`, so a router-initiated drain never strands a
        caller on a future nothing will resolve."""
        self.draining = True
        deadline = time.monotonic() + grace_s
        while self._inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        if self._unsubscribe is not None:
            self._unsubscribe()
        self._tcp.shutdown()
        self._tcp.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        # detach under the same lock `_serving()` builds under — a
        # handler still lazily building the tier must never race the
        # teardown's write; close() runs outside the lock (it joins
        # serving threads and must not hold the server's lock doing it)
        with self._sub_lock:
            serving = self._sig_serving if self._sig_serving_owned else None
            if serving is not None:
                self._sig_serving = None
        if serving is not None:
            serving.close()

    def drain(self) -> dict:
        """Router/operator-initiated drain: refuse new verification
        work, drain-and-fail the serving queues (in-flight batches get
        their grace, queued futures fail with `DispatcherClosed` /
        `QueueClosed` instead of hanging). The RPC control surface
        (`shard_drain`) calls this; `stop()` completes the shutdown."""
        self.draining = True
        with self._sub_lock:
            serving = self._sig_serving
        if serving is not None and hasattr(serving, "close") \
                and self._sig_serving_owned:
            serving.close()
        return {"draining": True, "inflight": self._inflight}

    # -- head push (eth_subscribe newHeads parity) -------------------------

    def _on_head(self, block) -> None:
        note = (json.dumps({
            "jsonrpc": "2.0",
            "method": "shard_subscription",
            "params": {"subscription": "newHeads",
                       "result": codec.enc_block(block)},
        }) + "\n").encode()
        with self._sub_lock:
            targets = list(self._subscribers.items())
        for wfile, (lock, peer) in targets:
            try:
                with lock:
                    wfile.write(note)
                    wfile.flush()
            except (OSError, ValueError) as exc:
                # connection-level failures only: the peer reset/broke
                # the pipe (OSError) or the handler already closed its
                # wfile (ValueError). Anything else is a server bug and
                # must surface to the head-feed caller, not silently
                # unsubscribe a healthy peer.
                with self._sub_lock:
                    self._subscribers.pop(wfile, None)
                log.warning("dropping head subscriber %s: %s", peer, exc)

    # -- connection loop ---------------------------------------------------

    def _handle_connection(self, handler) -> None:
        write_lock = threading.Lock()
        slots = threading.BoundedSemaphore(max(1, CONN_CONCURRENCY))
        workers = []

        def serve_one(raw: bytes) -> None:
            try:
                try:
                    response = self._dispatch(raw, handler, write_lock)
                finally:
                    with self._sub_lock:
                        self._inflight -= 1
                if response is not None:
                    with write_lock:
                        handler.wfile.write(
                            (json.dumps(response) + "\n").encode())
                        handler.wfile.flush()
            except (OSError, ValueError):
                pass  # peer gone mid-response: its client already knows
            finally:
                slots.release()

        try:
            for raw in handler.rfile:
                raw = raw.strip()
                if not raw:
                    continue
                with self._sub_lock:
                    self._inflight += 1
                # concurrent dispatch, bounded: responses multiplex back
                # by request id (the client's pending map reorders), and
                # once CONN_CONCURRENCY requests are in flight the read
                # loop blocks here — TCP backpressure to the sender
                slots.acquire()
                worker = threading.Thread(target=serve_one, args=(raw,),
                                          daemon=True,
                                          name="rpc-conn-worker")
                workers.append(worker)
                worker.start()
                if len(workers) > CONN_CONCURRENCY:
                    workers = [w for w in workers if w.is_alive()]
        except (OSError, ValueError):
            pass
        finally:
            # drain in-flight workers briefly (shared deadline, not
            # per-thread): their responses are undeliverable now, and
            # they are daemons — this just keeps teardown orderly
            deadline = time.monotonic() + 1.0
            for worker in workers:
                worker.join(timeout=max(0.0, deadline - time.monotonic()))
            with self._sub_lock:
                self._subscribers.pop(handler.wfile, None)
                self._p2p_challenges.pop(handler.wfile, None)
                dead = [pid for pid, (wf, _) in self._p2p_peers.items()
                        if wf is handler.wfile]
                for pid in dead:
                    self._p2p_peers.pop(pid, None)
                    self._p2p_meta.pop(pid, None)

    def _dispatch(self, raw: bytes, handler, write_lock) -> Optional[dict]:
        try:
            req = json.loads(raw)
        except json.JSONDecodeError:
            return {"jsonrpc": "2.0", "id": None,
                    "error": {"code": INVALID_REQUEST, "message": "bad json"}}
        rid = req.get("id")
        method = req.get("method", "")
        params = req.get("params", [])
        trace_id = None
        handler_span_id = None
        with self._sub_lock:
            self.method_calls[method] = self.method_calls.get(method, 0) + 1
        try:
            if method == "shard_subscribe":
                try:
                    peer = "%s:%d" % handler.client_address[:2]
                except (TypeError, IndexError):
                    peer = repr(handler.client_address)
                with self._sub_lock:
                    self._subscribers[handler.wfile] = (write_lock, peer)
                result = "newHeads"
            elif method == "shard_p2pChallenge":
                import secrets

                nonce = secrets.token_bytes(32)
                with self._sub_lock:
                    self._p2p_challenges[handler.wfile] = nonce
                result = nonce.hex()
            elif method == "shard_p2pAttach":
                handshake = params[0] if params else {}
                self._check_handshake(handshake)
                account = self._check_attach_signature(handshake, handler)
                endpoint = handshake.get("endpoint")
                with self._sub_lock:
                    peer_id = self._p2p_ids
                    self._p2p_ids += 1
                    self._p2p_peers[peer_id] = (handler.wfile, write_lock)
                    self._p2p_meta[peer_id] = {
                        "account": account,
                        "endpoint": (None if endpoint is None
                                     else list(endpoint)),
                        "version": handshake.get(
                            "version", P2P_PROTOCOL_VERSION),
                    }
                result = peer_id
            else:
                fn = getattr(self, "rpc_" + method.replace("shard_", "", 1),
                             None)
                if fn is None:
                    return {"jsonrpc": "2.0", "id": rid,
                            "error": {"code": METHOD_NOT_FOUND,
                                      "message": f"unknown method {method}"}}
                # per-request handler span: parents any serving-tier
                # request spans the handler submits (the cross-process
                # attribution seam), and its trace id rides back to the
                # client on the response envelope. Extra envelope keys
                # are legal JSON-RPC: clients read `result`/`error` only.
                # An inbound `trace` envelope (RPCClient.call attaches
                # the caller's span context) is ADOPTED: the handler
                # span joins the remote trace and parents under the
                # remote span, stitching a router-traced request into
                # this replica's spans.
                if method in codec.TRACE_PLANE_METHODS:
                    # the trace plane is invisible to tracing (see
                    # codec.TRACE_PLANE_METHODS): no handler span, no
                    # trace fields on the response envelope
                    result = fn(*params)
                else:
                    inbound = req.get("trace")
                    ctx = None
                    if isinstance(inbound, dict):
                        ctx = (inbound.get("trace_id"),
                               inbound.get("span_id"))
                    with tracing.span(f"rpc/{method}",
                                      ctx=ctx) as handler_span:
                        result = fn(*params)
                    trace_id = handler_span.trace_id
                    handler_span_id = handler_span.span_id
        except SMCRevert as exc:
            return {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": REVERT_CODE, "message": str(exc),
                              "data": "SMCRevert"}}
        except Exception as exc:  # noqa: BLE001 - RPC boundary
            log.exception("rpc %s failed", method)
            return {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": INTERNAL_ERROR, "message": str(exc)}}
        if rid is None:
            return None  # notification
        response = {"jsonrpc": "2.0", "id": rid, "result": result}
        if trace_id is not None:
            response["trace"] = trace_id
            # the full handler context alongside the bare id (kept for
            # older clients): span_id lets the caller and the fleet
            # collector stitch THIS request/response pair exactly —
            # a trace id alone is ambiguous under retries and hedges
            response["traceCtx"] = {"trace_id": trace_id,
                                    "span_id": handler_span_id}
        return response

    # -- method surface (shard_* namespace) --------------------------------
    # views

    def rpc_blockNumber(self):
        return self.backend.block_number

    def rpc_currentPeriod(self):
        return self.backend.current_period()

    def rpc_blockByNumber(self, number=None):
        return codec.enc_block(self.backend.block_by_number(number))

    def rpc_shardCount(self):
        return self.backend.smc.shard_count

    def rpc_getNotaryInCommittee(self, sender, shard_id):
        return codec.enc_bytes(self.backend.get_notary_in_committee(
            Address20(codec.dec_bytes(sender)), shard_id))

    def rpc_committeeContext(self):
        ctx = self.backend.committee_context()
        return {
            "period": ctx["period"],
            "sampleSize": ctx["sample_size"],
            "blockhash": codec.enc_bytes(ctx["blockhash"]),
            "pool": [None if a is None else codec.enc_bytes(a)
                     for a in ctx["pool"]],
        }

    def rpc_notaryRegistry(self, address):
        return codec.enc_registry(self.backend.notary_registry(
            Address20(codec.dec_bytes(address))))

    def rpc_collationRecord(self, shard_id, period):
        return codec.enc_record(self.backend.collation_record(shard_id, period))

    def rpc_lastSubmittedCollation(self, shard_id):
        return self.backend.last_submitted_collation(shard_id)

    def rpc_lastApprovedCollation(self, shard_id):
        return self.backend.last_approved_collation(shard_id)

    def rpc_notaryByPoolIndex(self, index):
        addr = self.backend.notary_by_pool_index(index)
        return None if addr is None else codec.enc_bytes(addr)

    def rpc_hasVoted(self, shard_id, index):
        return self.backend.smc.has_voted(shard_id, index)

    def rpc_getVoteCount(self, shard_id):
        return self.backend.smc.get_vote_count(shard_id)

    def rpc_balanceOf(self, address):
        return self.backend.balance_of(Address20(codec.dec_bytes(address)))

    def rpc_transactionReceipt(self, tx_hash):
        receipt = self.backend.transaction_receipt(
            Hash32(codec.dec_bytes(tx_hash)))
        return None if receipt is None else codec.enc_receipt(receipt)

    def rpc_traceTransaction(self, tx_hash):
        """The debug_traceTransaction role (`eth/api_tracer.go`) for the
        native engine: the SMC's emitted events ARE the execution trace
        (one entry per state-machine effect), returned with the receipt
        frame. None for unknown hashes."""
        receipt = self.backend.transaction_receipt(
            Hash32(codec.dec_bytes(tx_hash)))
        if receipt is None:
            return None
        def enc_arg(value):
            if isinstance(value, (bytes, bytearray)) \
                    or hasattr(value, "__bytes__"):  # Address20 / Hash32
                return codec.enc_bytes(bytes(value))
            return value

        return {
            "txHash": codec.enc_bytes(receipt.tx_hash),
            "status": receipt.status,
            "blockNumber": receipt.block_number,
            "trace": [{"event": e.name,
                       "args": {k: enc_arg(v) for k, v in e.args.items()}}
                      for e in receipt.events],
        }

    def rpc_verifyPeriodBatch(self, period):
        return self.backend.verify_period_batch(period)

    # -- verification serving (the coalescing tier) ------------------------

    def _serving(self):
        """The shared serving backend, built on first use. Injected
        backends that already expose `submit` (a `ServingSigBackend`)
        are used as-is (and not closed by us); a plain `SigBackend`
        gets wrapped."""
        with self._sub_lock:
            if self._sig_serving is None:
                inner = self._sig_backend
                if inner is not None and hasattr(inner, "submit"):
                    self._sig_serving = inner
                else:
                    from gethsharding_tpu.serving import ServingSigBackend
                    from gethsharding_tpu.sigbackend import get_backend

                    self._sig_serving = ServingSigBackend(
                        inner or get_backend("python"))
                    self._sig_serving_owned = True
            return self._sig_serving

    def _check_accepting(self, method: str) -> None:
        if self.draining:
            # the router's retry ladder keys on this phrase: a draining
            # replica is a routing fact, not a caller error
            raise RuntimeError(f"replica draining: {method} refused")

    def rpc_ecrecover(self, digests, sigs, klass=None, tenant=None):
        """Batch address recovery for external clients (txpool feeders,
        light verifiers). The serving backend's sync face enqueues and
        parks the handler thread on the request's future — while this
        batch waits out its flush window, other connection threads
        enqueue into the SAME dispatch, so N concurrent small requests
        cost one device batch instead of N. (The sync face also records
        the future_wake trace phase — one await-then-wake sequence for
        every entry point, serving/backend.py.) The optional trailing
        `klass`/`tenant` params tag the request's admission class and
        quota bucket (serving/classes.py) — a catch-up replayer passes
        ``"catchup_replay"`` and is shed first under overload."""
        self._check_accepting("shard_ecrecover")
        from gethsharding_tpu.serving.classes import admission_class

        serving = self._serving()
        digests = [codec.dec_bytes(d) for d in digests]
        sigs = [codec.dec_bytes(s) for s in sigs]
        if klass is not None or tenant is not None:
            # tenant without class still enters the context: the quota
            # must charge the tenant even when the caller says nothing
            # about class (default interactive, this op's default)
            with admission_class(klass or "interactive", tenant):
                out = serving.ecrecover_addresses(digests, sigs)
        else:
            out = serving.ecrecover_addresses(digests, sigs)
        return [None if addr is None else codec.enc_bytes(bytes(addr))
                for addr in out]

    def rpc_verifyAggregates(self, messages, agg_sigs, agg_pks,
                             klass=None, tenant=None):
        """Batch aggregate-vote verification over the serving tier (the
        coalescing analog of the notary's bls_verify_aggregates); the
        optional trailing params tag the admission class like
        shard_ecrecover's."""
        self._check_accepting("shard_verifyAggregates")
        from gethsharding_tpu.serving.classes import admission_class

        serving = self._serving()
        args = ([codec.dec_bytes(m) for m in messages],
                [codec.dec_g1(s) for s in agg_sigs],
                [codec.dec_g2(p) for p in agg_pks])
        if klass is not None or tenant is not None:
            # see shard_ecrecover: a tenant tag alone still charges the
            # quota under this op's default class
            with admission_class(klass or "interactive", tenant):
                out = serving.bls_verify_aggregates(*args)
        else:
            out = serving.bls_verify_aggregates(*args)
        return [bool(b) for b in out]

    def rpc_verifyCommittees(self, messages, sig_rows, pk_rows,
                             pk_row_keys=None, klass=None, tenant=None):
        """The committee plane over the wire: batch aggregate-and-
        verify of per-row vote signatures + member pubkeys through the
        serving tier (the op the notary's period audit drives — with
        this RPC a fleet frontend balances audits cross-process
        instead of pinning them to the caller's device). `pk_row_keys`
        are the optional per-row pk-plane cache keys (wire form:
        codec.enc_pk_row_keys), so a repeat committee stays
        device-resident on the replica exactly as it would in-process.
        The optional trailing `klass`/`tenant` tag admission like
        shard_ecrecover's (a notary's bulk_audit context rides the
        wire as an explicit klass; tenant-only still charges the quota
        under this op's default class)."""
        self._check_accepting("shard_verifyCommittees")
        from gethsharding_tpu.serving.classes import admission_class

        serving = self._serving()
        args = ([codec.dec_bytes(m) for m in messages],
                codec.dec_g1_rows(sig_rows),
                codec.dec_g2_rows(pk_rows))
        keys = None if pk_row_keys is None else [
            None if k is None else str(k) for k in pk_row_keys]
        if klass is not None or tenant is not None:
            with admission_class(klass or "interactive", tenant):
                out = serving.bls_verify_committees(*args,
                                                    pk_row_keys=keys)
        else:
            out = serving.bls_verify_committees(*args, pk_row_keys=keys)
        return [bool(b) for b in out]

    def rpc_dasVerify(self, chunks, indices, proofs, roots,
                      klass=None, tenant=None):
        """The DAS sample-verdict plane over the wire: one verdict per
        (chunk, index, proof path, root) row through the serving tier
        (serving op `das_verify`, default class bulk_audit via the
        per-op map). Malformed rows cost a False verdict, never an
        error — the same hostile-input contract as the in-process op."""
        self._check_accepting("shard_dasVerify")
        from gethsharding_tpu.serving.classes import admission_class

        serving = self._serving()
        args = codec.dec_das_call(chunks, indices, proofs, roots)
        if klass is not None or tenant is not None:
            with admission_class(klass or "bulk_audit", tenant):
                out = serving.das_verify_samples(*args)
        else:
            out = serving.das_verify_samples(*args)
        return [bool(b) for b in out]

    def rpc_dasPolyVerify(self, commitments, index_rows, eval_rows,
                          proofs, ns, klass=None, tenant=None):
        """The DAS multiproof-verdict plane over the wire: one verdict
        per sampled collation row (64-byte poly commitment, sampled
        index set, claimed evaluations, 64-byte multiproof, domain
        size) through the serving tier (serving op `das_poly_verify`,
        default class bulk_audit via the per-op map; light clients
        pass `interactive`). Malformed rows cost a False verdict,
        never an error."""
        self._check_accepting("shard_dasPolyVerify")
        from gethsharding_tpu.serving.classes import admission_class

        serving = self._serving()
        args = codec.dec_das_poly_call(commitments, index_rows, eval_rows,
                                       proofs, ns)
        if klass is not None or tenant is not None:
            with admission_class(klass or "bulk_audit", tenant):
                out = serving.das_verify_multiproofs(*args)
        else:
            out = serving.das_verify_multiproofs(*args)
        return [bool(b) for b in out]

    def rpc_health(self):
        """The replica-health surface a fleet router sweeps: the drain
        flag, the failover breaker's state (if the injected backend
        composes one), and the serving tier's per-class queue depths.
        One round trip, cheap enough for sub-second polling."""
        from gethsharding_tpu.fleet.router import breaker_of

        payload = {"draining": self.draining,
                   # minus one: this health request is itself in flight
                   "inflight": max(0, self._inflight - 1),
                   "breaker": None, "serving": None}
        backend = self._sig_backend
        if backend is not None:
            breaker = breaker_of(backend)
            if breaker is not None:
                payload["breaker"] = breaker.state_name
        with self._sub_lock:
            serving = self._sig_serving
        batcher = getattr(serving, "batcher", None)
        if batcher is None:
            # the serving tier may hide under a failover/soundness face
            probe, hops = serving, 0
            while probe is not None and hops < 8 and batcher is None:
                batcher = getattr(probe, "batcher", None)
                probe, hops = getattr(probe, "inner", None), hops + 1
        if batcher is not None:
            payload["serving"] = {
                "shed": batcher.shed_by_class(),
                "quota_rejections": batcher.quota_rejections(),
                "depth": {op: batcher.class_depths(op)
                          for op in batcher.dispatch_counts},
            }
        return payload

    def rpc_drain(self):
        """Router/operator-initiated drain (see `drain()`)."""
        return self.drain()

    def rpc_metrics(self):
        """Metrics federation: this replica's full registry snapshot in
        ONE round trip — the scrape the fleet router's background
        health sweep folds into its own registry under
        ``fleet/replica/<name>/...`` (plus fleet-level aggregates), so
        a router's /status answers "which replica's chip is slow"
        without dialing N dashboards. Snapshots are plain JSON-safe
        dicts (counters/gauges/timers/histograms)."""
        from gethsharding_tpu.metrics import DEFAULT_REGISTRY

        return DEFAULT_REGISTRY.snapshot()

    # -- fleet tracing (the fleettrace control surface) --------------------

    def rpc_traceHandshake(self):
        """Clock-offset handshake: the exporter reads this process's
        wall clock mid-round-trip (NTP midpoint estimate) to measure
        the per-connection skew it stamps on every span batch — the
        cross-HOST extension of the `clock_offset_us` anchor."""
        import os

        from gethsharding_tpu.tracing.export import clock_offset_us

        return {"wall_us": time.time() * 1e6,
                "clock_offset_us": clock_offset_us(),
                "pid": os.getpid()}

    def rpc_traceExport(self, payload):
        """Span-batch sink: accept one exporter batch into this
        process's fleettrace collector (``accepted: false`` when no
        collector is booted — a replica is a producer, not an owner)."""
        from gethsharding_tpu import fleettrace

        collector = fleettrace.active()
        if collector is None:
            return {"accepted": False, "spans": 0}
        return collector.ingest_payload(payload)

    def rpc_traceAttribution(self):
        """Per-class critical-path attribution tables (None when no
        collector is booted)."""
        from gethsharding_tpu import fleettrace

        collector = fleettrace.active()
        return None if collector is None else collector.attribution()

    def rpc_traceExemplars(self, limit=8):
        """Most recent retained (tail-sampled) assembled traces,
        newest first — full span trees, the post-mortem payload."""
        from gethsharding_tpu import fleettrace

        collector = fleettrace.active()
        return [] if collector is None else collector.exemplars(
            limit=int(limit))

    # -- on-demand profiling (the devscope control surface) ----------------

    def rpc_profileStart(self, mode=None, hz=None):
        """Begin an on-demand profiling session on THIS process:
        ``mode`` = ``sampler`` (pure-Python collapsed-stack sampler),
        ``jax`` (a jax.profiler trace into the bounded devscope
        profile directory), or ``both`` (the default). Idempotent — a
        session already running is reported, never doubled. The
        StatusServer's ``/profile?action=start`` drives the same
        manager."""
        from gethsharding_tpu.devscope import PROFILER

        return PROFILER.start(mode=mode,
                              hz=None if hz is None else float(hz))

    def rpc_profileStop(self):
        """End the profiling session (no-op when none is running);
        returns the session summary incl. the jax trace directory and
        the sampler's sample counts."""
        from gethsharding_tpu.devscope import PROFILER

        return PROFILER.stop()

    def rpc_profileStacks(self):
        """The sampler's collapsed-stack text (running session, or the
        last finished one) — the RPC twin of ``/profile/stacks`` for
        processes that serve no StatusServer (chain_server replicas)."""
        from gethsharding_tpu.devscope import PROFILER

        return PROFILER.stacks()

    def rpc_devscopeStatus(self):
        """The device-introspection snapshot (memory poller, compile
        watch, profiler) — what a node's /status ``devscope`` section
        shows, for RPC-only processes."""
        from gethsharding_tpu.devscope import devscope_status

        return devscope_status()

    def rpc_servingStats(self):
        """Dispatch/coalescing counters of the serving tier (None until
        the first submit builds it)."""
        with self._sub_lock:
            serving = self._sig_serving
        if serving is None or not hasattr(serving, "batcher"):
            return None
        return {"dispatches": dict(serving.batcher.dispatch_counts),
                "shed": serving.batcher.shed_counts()}

    # -- data-availability sampling (the light-client sample surface) ------

    def rpc_getSample(self, shard_id, period, indices):
        """Sampled chunks + inclusion proofs for (shard, period) from
        this process's DAS provider — the RPC (light-client) face of
        the shardp2p DASampleRequest flow: a client that can reach no
        sampling peers still gets proof-carrying samples it verifies
        locally against the returned commitment. None when no provider
        holds the blob."""
        if self._das is None:
            return None
        from gethsharding_tpu.das.service import MAX_SAMPLE_INDICES

        status = self._das.da_status(int(shard_id), int(period))
        if not status.get("known"):
            return None
        samples = []
        # same per-request cap as the p2p serving side
        for index in list(indices)[:MAX_SAMPLE_INDICES]:
            sample = self._das.get_sample(int(shard_id), int(period),
                                          int(index))
            if sample is None:
                continue
            samples.append({
                "index": sample["index"],
                "chunk": codec.enc_bytes(sample["chunk"]),
                "proof": [codec.enc_bytes(node)
                          for node in sample["proof"]],
            })
        commitment = self._das.commitment(int(shard_id), int(period))
        out = {
            "dasRoot": codec.enc_bytes(commitment.das_root),
            "chunkRoot": codec.enc_bytes(commitment.chunk_root),
            "k": commitment.k,
            "n": commitment.n,
            "bodyLen": commitment.body_len,
            "signature": codec.enc_bytes(commitment.signature),
            "samples": samples,
        }
        # poly plane: under --da-proofs=poly the k merkle paths above
        # collapse to ONE constant-size multiproof over the whole set
        # (das/pcs.py) — the client verifies it against polyCommitment
        poly = bytes(getattr(commitment, "poly_commitment", b""))
        if poly:
            out["polyCommitment"] = codec.enc_bytes(poly)
        if getattr(self._das, "proof_mode", "merkle") == "poly":
            multi = self._das.get_multiproof(
                int(shard_id), int(period),
                [int(i) for i in list(indices)[:MAX_SAMPLE_INDICES]])
            if multi is not None:
                out["multiproof"] = {
                    "indices": list(multi["indices"]),
                    "chunks": [codec.enc_bytes(c)
                               for c in multi["chunks"]],
                    "proof": codec.enc_bytes(multi["proof"]),
                }
        return out

    def rpc_daStatus(self, shard_id, period):
        """Is a DAS commitment known for (shard, period), and what
        shape is the erasure extension? `known: false` with
        `provider: false` means this process runs no DAS plane at
        all."""
        if self._das is None:
            return {"known": False, "provider": False,
                    "shard_id": int(shard_id), "period": int(period)}
        status = self._das.da_status(int(shard_id), int(period))
        status["provider"] = True
        return status

    # transactions

    def rpc_registerNotary(self, sender, bls_pubkey=None, bls_pop=None):
        return codec.enc_receipt(self.backend.register_notary(
            Address20(codec.dec_bytes(sender)),
            bls_pubkey=codec.dec_g2(bls_pubkey),
            bls_pop=codec.dec_g1(bls_pop)))

    def rpc_deregisterNotary(self, sender):
        return codec.enc_receipt(self.backend.deregister_notary(
            Address20(codec.dec_bytes(sender))))

    def rpc_releaseNotary(self, sender):
        return codec.enc_receipt(self.backend.release_notary(
            Address20(codec.dec_bytes(sender))))

    def rpc_addHeader(self, sender, shard_id, period, chunk_root, signature):
        return codec.enc_receipt(self.backend.add_header(
            Address20(codec.dec_bytes(sender)), shard_id, period,
            Hash32(codec.dec_bytes(chunk_root)),
            codec.dec_bytes(signature)))

    def rpc_submitVote(self, sender, shard_id, period, index, chunk_root,
                       bls_sig=None):
        return codec.enc_receipt(self.backend.submit_vote(
            Address20(codec.dec_bytes(sender)), shard_id, period, index,
            Hash32(codec.dec_bytes(chunk_root)),
            bls_sig=codec.dec_g1(bls_sig)))

    # dev-mode chain control (the SimulatedBackend Commit/FastForward
    # surface, exposed so a test/driver process can steer the chain)

    # shardp2p relay (the cross-process feed-bus transport; see
    # gethsharding_tpu/p2p/remote.py)

    def _p2p_push(self, peer_id, note_bytes) -> bool:
        with self._sub_lock:
            entry = self._p2p_peers.get(peer_id)
        if entry is None:
            return False
        wfile, lock = entry
        try:
            with lock:
                wfile.write(note_bytes)
                wfile.flush()
            return True
        except OSError:
            with self._sub_lock:
                self._p2p_peers.pop(peer_id, None)
            return False

    @staticmethod
    def _p2p_note(to_id, from_id, kind, payload) -> bytes:
        return (json.dumps({
            "jsonrpc": "2.0", "method": "shard_p2p",
            "params": {"to": to_id, "from": from_id, "type": kind,
                       "payload": payload},
        }) + "\n").encode()

    def _check_handshake(self, handshake: dict) -> None:
        """Protocol/version/network gate (p2p/protocol.go + the eth status
        exchange, scoped to the relay's trust model). Absent fields pass —
        an attacher that states nothing claims nothing — but any STATED
        field must match."""
        proto = handshake.get("protocol", P2P_PROTOCOL_NAME)
        if proto != P2P_PROTOCOL_NAME:
            raise ValueError(f"protocol mismatch: {proto!r}")
        version = handshake.get("version", P2P_PROTOCOL_VERSION)
        if version != P2P_PROTOCOL_VERSION:
            raise ValueError(
                f"version mismatch: peer {version}, ours {P2P_PROTOCOL_VERSION}")
        network = handshake.get("network_id")
        ours = self.backend.config.network_id
        if network is not None and network != ours:
            raise ValueError(f"network mismatch: peer {network}, ours {ours}")

    def _check_attach_signature(self, handshake: dict, handler) -> str:
        """Authenticated attach: the claimed account must be PROVEN by a
        secp256k1 signature over a challenge this relay issued on this
        connection. Unsigned or forged attaches are refused — the
        reference's RLPx authenticates both ends cryptographically
        (p2p/rlpx.go:178); a self-claimed identity would let any process
        impersonate a notary on the data-availability plane."""
        from gethsharding_tpu.p2p import direct

        account = handshake.get("account")
        sig_hex = handshake.get("sig")
        if not account or not sig_hex:
            raise ValueError(
                "unsigned attach refused: account + sig required")
        with self._sub_lock:
            challenge = self._p2p_challenges.pop(handler.wfile, None)
        if challenge is None:
            raise ValueError(
                "no pending challenge: call shard_p2pChallenge first")
        digest = direct.attach_digest(self.backend.config.network_id,
                                      challenge)
        if not direct.prove(digest, bytes.fromhex(sig_hex), account):
            raise ValueError(
                "attach signature does not prove the claimed account")
        return account.lower().removeprefix("0x")

    def rpc_p2pPeers(self):
        """Attached-peer table (admin_peers parity for the relay)."""
        with self._sub_lock:
            return [{"id": pid, **self._p2p_meta.get(pid, {})}
                    for pid in sorted(self._p2p_peers)]

    def rpc_networkId(self):
        return self.backend.config.network_id

    def rpc_auditData(self, period):
        """Bulk period-audit pull (records + vote sigs + voter pubkeys):
        ONE round trip for what would be O(shards) record reads plus
        O(votes) registry lookups (mainchain/mirror.assemble_audit_data)."""
        from gethsharding_tpu.mainchain.mirror import assemble_audit_data

        return assemble_audit_data(self.backend, period)

    def rpc_mirrorSnapshot(self):
        """Bulk state-mirror pull: ONE round trip for what would be
        ~3 calls per shard (mainchain/mirror.py)."""
        from gethsharding_tpu.mainchain.mirror import assemble_snapshot

        return assemble_snapshot(self.backend)

    def rpc_chainConfig(self):
        """The chain process's protocol constants — attached actors adopt
        these instead of trusting their own flags (one source of truth
        for period/committee math across processes)."""
        import dataclasses

        return dataclasses.asdict(self.backend.config)

    def rpc_p2pDetach(self, peer_id):
        with self._sub_lock:
            self._p2p_peers.pop(peer_id, None)
            self._p2p_meta.pop(peer_id, None)
        return True

    def rpc_p2pPeerInfo(self, peer_id):
        """Introduction lookup: the proven account + direct-listener
        endpoint for one peer (None if unknown)."""
        with self._sub_lock:
            meta = self._p2p_meta.get(peer_id)
        return None if meta is None else dict(meta)

    def rpc_p2pStats(self):
        return {"relayed_sends": self.p2p_relayed_sends,
                "peers": len(self._p2p_peers)}

    def rpc_methodStats(self):
        """Per-method request counts (chatter observability: the mirror's
        O(1)-per-head contract is asserted against these)."""
        with self._sub_lock:
            return dict(self.method_calls)

    def rpc_p2pSend(self, from_id, to_id, kind, payload):
        # handler threads are concurrent: the relayed-sends count is a
        # read-modify-write and takes the same lock as the peer tables
        with self._sub_lock:
            self.p2p_relayed_sends += 1
        return self._p2p_push(to_id,
                              self._p2p_note(to_id, from_id, kind, payload))

    def rpc_p2pBroadcast(self, from_id, kind, payload):
        with self._sub_lock:
            targets = [pid for pid in self._p2p_peers if pid != from_id]
        delivered = 0
        for pid in targets:
            if self._p2p_push(pid, self._p2p_note(pid, from_id, kind,
                                                  payload)):
                delivered += 1
        return delivered

    def rpc_fund(self, address, amount):
        self.backend.fund(Address20(codec.dec_bytes(address)), amount)
        return True

    def rpc_commit(self):
        return codec.enc_block(self.backend.commit())

    def rpc_fastForward(self, periods):
        self.backend.fast_forward(periods)
        return self.backend.block_number

    def rpc_setHead(self, number):
        """Dev-mode rollback (debug_setHead parity)."""
        return codec.enc_block(self.backend.set_head(number))

    def rpc_blockRange(self, start, end):
        """Blocks [start, end] inclusive — the header-download surface a
        follower chain process syncs from (eth/downloader role)."""
        start, end = int(start), int(end)
        if start < 0 or end > self.backend.block_number or end - start > 4096:
            raise ValueError("bad block range")
        return [codec.enc_block(self.backend.block_by_number(n))
                for n in range(start, end + 1)]

    def rpc_stateCheckpoint(self):
        """Full-state checkpoint at the current head (the fast-sync
        pivot-state analog) for follower chain processes."""
        return self.backend.state_checkpoint()

    def rpc_stateSeq(self):
        """Cheap state identity for followers' steady-state polling."""
        return self.backend.state_seq()
