"""RPC client + RemoteMainchain: dial a chain process and act on it.

Parity: `ethclient` + `sharding/mainchain/utils.go:17-22` (dialRPC).
`RemoteMainchain` implements the same backend surface as
`SimulatedMainchain` (duck-typed), so `SMCClient(backend=RemoteMainchain
.dial(...))` turns any sharding actor into a genuinely separate OS
process from the chain — the reference's process topology (N actor
processes <-> one mainchain node over RPC).

A background reader thread routes responses by id and dispatches
`shard_subscription` notifications to head subscribers (the
`SubscribeNewHead` flow, `sharding/notary/notary.go:33-38`).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import queue
import socket
import threading
from dataclasses import dataclass
from typing import Callable, List, Optional

from gethsharding_tpu import tracing
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.smc.state_machine import SMCRevert
from gethsharding_tpu.utils.hexbytes import Address20, Hash32

log = logging.getLogger("rpc.client")


class RPCError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"rpc error {code}: {message}")
        self.code = code
        self.message = message


def _dec_block(obj: dict):
    """ONE decoder for the block wire shape (codec.dec_block) — a local
    duplicate here silently dropped the `extra` (engine seal) field when
    enc_block grew it."""
    return codec.dec_block(obj)


@dataclass
class RemoteReceipt:
    tx_hash: Hash32
    status: int
    block_number: int


class RPCClient:
    """Newline-delimited JSON-RPC 2.0 over a stream socket."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=10.0)
        self._sock.settimeout(None)
        self._file = self._sock.makefile("rwb")
        self._ids = itertools.count(1)
        self._pending: dict = {}
        self._pending_lock = threading.Lock()
        self._write_lock = threading.Lock()
        self._head_subscribers: List[Callable] = []
        self._notification_hooks: dict = {}
        self._timeout = timeout
        self._closed = False
        # notifications are dispatched OFF the reader thread: subscriber
        # callbacks (e.g. the notary head loop) issue further RPC calls,
        # which would deadlock if the reader were blocked inside them
        self._notifications: "queue.Queue" = queue.Queue()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="rpc-client-dispatch")
        self._dispatcher.start()
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="rpc-client-reader")
        self._reader.start()

    def close(self) -> None:
        self._closed = True
        self._notifications.put(None)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        # drain both service threads (bounded: the socket is dead and the
        # dispatch queue got its sentinel, so neither can block long).
        # A subscriber callback may close() from the dispatcher thread
        # itself — never join the current thread.
        me = threading.current_thread()
        for thread in (self._dispatcher, self._reader):
            if thread is not me:
                thread.join(timeout=5.0)

    # -- request/response --------------------------------------------------

    def call(self, method: str, *params):
        rid = next(self._ids)
        event = threading.Event()
        slot: dict = {"event": event}
        with self._pending_lock:
            self._pending[rid] = slot
        # cross-process trace propagation: the caller's active span
        # context rides the request as a `trace` envelope field, and the
        # server adopts it as its handler span's trace/parent — one
        # trace id from a router's route span down into the replica's
        # dispatch spans. Extra envelope keys are legal JSON-RPC.
        # Trace-plane methods get NO span and NO envelope: a span per
        # shipped batch re-enters the export buffer it ships (see
        # codec.TRACE_PLANE_METHODS).
        span_cm = (contextlib.nullcontext()
                   if method in codec.TRACE_PLANE_METHODS
                   else tracing.span(f"rpc/client/{method}"))
        with span_cm as client_span:
            request = {"jsonrpc": "2.0", "id": rid, "method": method,
                       "params": list(params)}
            ctx = (tracing.current_context()
                   if client_span is not None else None)
            if ctx is not None:
                request["trace"] = {"trace_id": ctx[0], "span_id": ctx[1]}
            payload = (json.dumps(request) + "\n").encode()
            try:
                with self._write_lock:
                    self._file.write(payload)
                    self._file.flush()
            except (OSError, ValueError):
                # dead socket (the server was killed/restarted): the
                # reply will never come — reclaim the pending slot
                # instead of leaking it, and let the caller's
                # transport-error handling (e.g. RpcReplicaBackend's
                # redial) classify the failure
                with self._pending_lock:
                    self._pending.pop(rid, None)
                raise
            if not event.wait(self._timeout):
                with self._pending_lock:
                    self._pending.pop(rid, None)
                raise TimeoutError(f"rpc call {method} timed out")
            if "trace" in slot and client_span is not None:
                # the server's handler trace id: equal to ours once the
                # server stitches, the REMOTE id against an older server
                # — either way caller logs correlate to replica traces
                client_span.tag(remote_trace=slot["trace"])
            ctx = slot.get("trace_ctx")
            if isinstance(ctx, dict) and client_span is not None:
                # newer servers also return the handler SPAN id: the
                # exact remote span this call produced, unambiguous
                # even when retries/hedges reuse one trace id
                client_span.tag(remote_span=ctx.get("span_id"))
            if "error" in slot:
                err = slot["error"]
                if err.get("data") == "SMCRevert":
                    raise SMCRevert(err.get("message", ""))
                raise RPCError(err.get("code", -1), err.get("message", ""))
            return slot.get("result")

    def subscribe_heads(self, callback: Callable) -> Callable[[], None]:
        # registration is caller-thread territory while the dispatcher
        # iterates a snapshot copy: the list mutations take the pending
        # lock so concurrent subscribe/unsubscribe can't lose entries
        with self._pending_lock:
            self._head_subscribers.append(callback)
        self.call("shard_subscribe", "newHeads")

        def unsubscribe() -> None:
            with self._pending_lock:
                if callback in self._head_subscribers:
                    self._head_subscribers.remove(callback)

        return unsubscribe

    def on_notification(self, method: str, callback: Callable) -> None:
        """Route push notifications with the given method (e.g. the
        shard_p2p relay) to `callback(params)` off the reader thread."""
        with self._pending_lock:
            self._notification_hooks[method] = callback

    def _read_loop(self) -> None:
        try:
            for raw in self._file:
                try:
                    msg = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                method = msg.get("method")
                if method == "shard_subscription":
                    self._notifications.put(
                        ("heads", _dec_block(msg["params"]["result"])))
                    continue
                if method in self._notification_hooks:
                    self._notifications.put((method, msg.get("params")))
                    continue
                rid = msg.get("id")
                with self._pending_lock:
                    slot = self._pending.pop(rid, None)
                if slot is not None:
                    if "trace" in msg:
                        # the handler-span trace id the server returns
                        # on the envelope — surfaced as the caller
                        # span's `remote_trace` tag (it was received
                        # and silently discarded before)
                        slot["trace"] = msg["trace"]
                    if "traceCtx" in msg:
                        slot["trace_ctx"] = msg["traceCtx"]
                    if "error" in msg:
                        slot["error"] = msg["error"]
                    else:
                        slot["result"] = msg.get("result")
                    slot["event"].set()
        except (OSError, ValueError):
            pass
        finally:
            if not self._closed:
                log.warning("rpc connection lost")
            self._notifications.put(None)
            # unblock all waiters
            with self._pending_lock:
                pending = list(self._pending.values())
                self._pending.clear()
            for slot in pending:
                slot["error"] = {"code": -32000, "message": "connection lost"}
                slot["event"].set()

    def _dispatch_loop(self) -> None:
        while True:
            item = self._notifications.get()
            if item is None:
                return
            method, payload = item
            if method == "heads":
                for callback in list(self._head_subscribers):
                    try:
                        callback(payload)
                    except Exception:  # noqa: BLE001 - subscriber owns it
                        log.exception("head subscriber failed")
                continue
            hook = self._notification_hooks.get(method)
            if hook is not None:
                try:
                    hook(payload)
                except Exception:  # noqa: BLE001
                    log.exception("notification hook %s failed", method)


class FrontendPool:
    """Actor-side failover across a fleet OF frontends.

    `ShardNode --fleet-frontend` used to pin an actor to ONE frontend
    process — its single point of failure. The pool dials every
    ``HOST:PORT`` in `endpoints` (lazily: a frontend still coming up
    joins on first use) and serves the full `SigBackend` verification
    surface, failing over between frontends EXACTLY like the router
    fails over between replicas — on the typed "replica draining" /
    connection-lost taxonomy that `fleet.router.RpcReplicaBackend`
    already folds into `ConnectionError`, plus per-call timeouts.

    The primary is STICKY: all calls go to one frontend until it fails,
    then the pool advances and stays there (a recovered frontend is a
    redial away whenever the rotation comes back around). A frontend
    stopping gracefully answers the drain-notice window with the typed
    refusal, so failover costs one round trip, not a burned retry on a
    connection reset."""

    def __init__(self, endpoints: List[str], timeout: float = 30.0):
        from gethsharding_tpu.fleet.router import RpcReplicaBackend

        if not endpoints:
            raise ValueError("FrontendPool needs at least one endpoint")
        self.endpoints = [str(e) for e in endpoints]
        self._backends = []
        for endpoint in self.endpoints:
            host, port = endpoint.rsplit(":", 1)
            self._backends.append(RpcReplicaBackend.dial_lazy(
                host, int(port), timeout=timeout))
        self._primary = 0
        self._lock = threading.Lock()
        self.failovers = 0

    @classmethod
    def dial(cls, spec: str, timeout: float = 30.0) -> "FrontendPool":
        """Build from the CLI's comma-separated ``HOST:PORT[,...]``."""
        endpoints = [e.strip() for e in spec.split(",") if e.strip()]
        return cls(endpoints, timeout=timeout)

    def _rotation(self):
        with self._lock:
            start = self._primary
        n = len(self._backends)
        return [(start + i) % n for i in range(n)]

    def _advance(self, from_index: int) -> None:
        with self._lock:
            if self._primary == from_index:
                self._primary = (from_index + 1) % len(self._backends)
                self.failovers += 1

    def _failover(self, fn):
        """Run `fn(backend)` against the sticky primary, advancing
        through the rotation on the retryable taxonomy; the LAST error
        propagates once every frontend has refused."""
        last_exc = None
        for index in self._rotation():
            backend = self._backends[index]
            try:
                return fn(backend)
            except (ConnectionError, TimeoutError) as exc:
                log.warning("frontend %s unavailable (%s); failing over",
                            backend.name, type(exc).__name__)
                self._advance(index)
                last_exc = exc
        raise last_exc

    # -- the SigBackend verification surface -------------------------------

    def ecrecover_addresses(self, digests, sigs65):
        return self._failover(
            lambda b: b.ecrecover_addresses(digests, sigs65))

    def bls_verify_aggregates(self, messages, agg_sigs, agg_pks):
        return self._failover(
            lambda b: b.bls_verify_aggregates(messages, agg_sigs,
                                              agg_pks))

    def bls_verify_committees(self, messages, sig_rows, pk_rows,
                              pk_row_keys=None):
        return self._failover(
            lambda b: b.bls_verify_committees(messages, sig_rows,
                                              pk_rows,
                                              pk_row_keys=pk_row_keys))

    def bls_verify_committees_async(self, messages, sig_rows, pk_rows,
                                    pk_row_keys=None):
        from gethsharding_tpu.sigbackend import VerdictFuture

        out = self.bls_verify_committees(messages, sig_rows, pk_rows,
                                         pk_row_keys=pk_row_keys)
        future = VerdictFuture(lambda: out)
        future.result()
        return future

    def das_verify_samples(self, chunks, indices, proofs, roots):
        return self._failover(
            lambda b: b.das_verify_samples(chunks, indices, proofs,
                                           roots))

    def das_verify_multiproofs(self, commitments, index_rows, eval_rows,
                               proofs, ns):
        return self._failover(
            lambda b: b.das_verify_multiproofs(commitments, index_rows,
                                               eval_rows, proofs, ns))

    # -- control plane -----------------------------------------------------

    def call(self, method: str, *params):
        """A raw control-plane RPC (``shard_fleetStatus``,
        ``shard_addReplica``, ...) with the same failover."""
        return self._failover(lambda b: b._call(method, *params))

    def health(self) -> dict:
        return self._failover(lambda b: b.health())

    def metrics(self) -> dict:
        return self._failover(lambda b: b.metrics())

    def primary(self) -> str:
        with self._lock:
            return self.endpoints[self._primary]

    def close(self) -> None:
        for backend in self._backends:
            try:
                backend.close()
            except Exception:  # noqa: BLE001 - already dead
                pass


class RemoteMainchain:
    """Client-side mainchain backend over RPC (SimulatedMainchain's duck
    type, minus in-process-only internals)."""

    def __init__(self, rpc: RPCClient):
        self.rpc = rpc

    @classmethod
    def dial(cls, host: str, port: int, timeout: float = 30.0
             ) -> "RemoteMainchain":
        return cls(RPCClient(host, port, timeout=timeout))

    def close(self) -> None:
        self.rpc.close()

    # chain reader
    @property
    def block_number(self) -> int:
        return self.rpc.call("shard_blockNumber")

    def current_period(self) -> int:
        return self.rpc.call("shard_currentPeriod")

    def block_by_number(self, number: Optional[int] = None):
        return _dec_block(self.rpc.call("shard_blockByNumber", number))

    def subscribe_new_head(self, callback) -> Callable[[], None]:
        return self.rpc.subscribe_heads(callback)

    # SMC views
    def get_notary_in_committee(self, sender: Address20, shard_id: int):
        return Address20(codec.dec_bytes(self.rpc.call(
            "shard_getNotaryInCommittee", codec.enc_bytes(sender), shard_id)))

    def notary_registry(self, address: Address20):
        return codec.dec_registry(self.rpc.call(
            "shard_notaryRegistry", codec.enc_bytes(address)))

    def committee_context(self) -> dict:
        ctx = self.rpc.call("shard_committeeContext")
        return {
            "period": ctx["period"],
            "sample_size": ctx["sampleSize"],
            "blockhash": codec.dec_bytes(ctx["blockhash"]),
            "pool": [None if a is None else codec.dec_bytes(a)
                     for a in ctx["pool"]],
        }

    def collation_record(self, shard_id: int, period: int):
        return codec.dec_record(self.rpc.call(
            "shard_collationRecord", shard_id, period))

    def last_submitted_collation(self, shard_id: int) -> int:
        return self.rpc.call("shard_lastSubmittedCollation", shard_id)

    def last_approved_collation(self, shard_id: int) -> int:
        return self.rpc.call("shard_lastApprovedCollation", shard_id)

    def notary_by_pool_index(self, index: int) -> Optional[Address20]:
        addr = self.rpc.call("shard_notaryByPoolIndex", index)
        return None if addr is None else Address20(codec.dec_bytes(addr))

    def has_voted(self, shard_id: int, index: int) -> bool:
        return self.rpc.call("shard_hasVoted", shard_id, index)

    def get_vote_count(self, shard_id: int) -> int:
        return self.rpc.call("shard_getVoteCount", shard_id)

    def shard_count(self) -> int:
        return self.rpc.call("shard_shardCount")

    def balance_of(self, account: Address20) -> int:
        return self.rpc.call("shard_balanceOf", codec.enc_bytes(account))

    def transaction_receipt(self, tx_hash: Hash32):
        obj = self.rpc.call("shard_transactionReceipt",
                            codec.enc_bytes(tx_hash))
        return None if obj is None else RemoteReceipt(
            tx_hash=Hash32(codec.dec_bytes(obj["txHash"])),
            status=obj["status"], block_number=obj["blockNumber"])

    def trace_transaction(self, tx_hash: Hash32):
        """Event-level execution trace of a sealed tx (the
        debug_traceTransaction analog); None for unknown hashes."""
        return self.rpc.call("shard_traceTransaction",
                             codec.enc_bytes(tx_hash))

    def verify_period_batch(self, period: int):
        return self.rpc.call("shard_verifyPeriodBatch", period)

    # transactions
    def register_notary(self, sender: Address20, value=None,
                        bls_pubkey=None, bls_pop=None) -> RemoteReceipt:
        return self._receipt(self.rpc.call(
            "shard_registerNotary", codec.enc_bytes(sender),
            codec.enc_g2(bls_pubkey), codec.enc_g1(bls_pop)))

    def deregister_notary(self, sender: Address20) -> RemoteReceipt:
        return self._receipt(self.rpc.call(
            "shard_deregisterNotary", codec.enc_bytes(sender)))

    def release_notary(self, sender: Address20) -> RemoteReceipt:
        return self._receipt(self.rpc.call(
            "shard_releaseNotary", codec.enc_bytes(sender)))

    def add_header(self, sender: Address20, shard_id: int, period: int,
                   chunk_root: Hash32, signature: bytes = b"") -> RemoteReceipt:
        return self._receipt(self.rpc.call(
            "shard_addHeader", codec.enc_bytes(sender), shard_id, period,
            codec.enc_bytes(chunk_root), codec.enc_bytes(signature)))

    def submit_vote(self, sender: Address20, shard_id: int, period: int,
                    index: int, chunk_root: Hash32,
                    bls_sig=None) -> RemoteReceipt:
        return self._receipt(self.rpc.call(
            "shard_submitVote", codec.enc_bytes(sender), shard_id, period,
            index, codec.enc_bytes(chunk_root), codec.enc_g1(bls_sig)))

    # dev-mode chain control
    def network_id(self) -> int:
        return self.rpc.call("shard_networkId")

    def mirror_snapshot(self) -> dict:
        """Bulk SMC state snapshot (json int keys restored in place)."""
        from gethsharding_tpu.mainchain.mirror import restore_int_keys

        return restore_int_keys(self.rpc.call("shard_mirrorSnapshot"))

    def audit_data(self, period: int) -> dict:
        """Bulk period-audit data (one round trip; shard keys restored)."""
        data = self.rpc.call("shard_auditData", period)
        data["shards"] = {int(k): v for k, v in data["shards"].items()}
        return data

    def chain_config(self, **overrides):
        """Fetch the chain process's protocol constants as a Config.
        `overrides` replace node-local knobs (e.g. windback_depth) that
        are not chain consensus parameters."""
        from gethsharding_tpu.params import Config

        fields = self.rpc.call("shard_chainConfig")
        fields.update(overrides)
        return Config(**fields)

    def p2p_peers(self) -> list:
        """The relay's attached-peer table (admin_peers analog)."""
        return self.rpc.call("shard_p2pPeers")

    def fund(self, account: Address20, amount: int) -> None:
        self.rpc.call("shard_fund", codec.enc_bytes(account), amount)

    def commit(self):
        return _dec_block(self.rpc.call("shard_commit"))

    def fast_forward(self, periods: int) -> int:
        return self.rpc.call("shard_fastForward", periods)

    def set_head(self, number: int):
        """Dev-mode chain rollback (smc/chain.py set_head)."""
        return _dec_block(self.rpc.call("shard_setHead", number))

    @staticmethod
    def _receipt(obj: dict) -> RemoteReceipt:
        return RemoteReceipt(tx_hash=Hash32(codec.dec_bytes(obj["txHash"])),
                             status=obj["status"],
                             block_number=obj["blockNumber"])
